"""`python -m lightgbm_tpu.profile` — op-level device profile of training.

Traces N boosting iterations on the real chip with the jax profiler, then
prints device time per XLA op name via the reusable xplane parser
(:mod:`lightgbm_tpu.telemetry.xplane`). The old top-level ``prof_trace.py``
dev script is now a thin wrapper over this entry point.

Usage: python -m lightgbm_tpu.profile [--shape NAME] [rows] [iters]
                                      [key=value ...]
       python -m lightgbm_tpu.profile --merge DIR [--run NAME]
                                      [--out PATH] [--json]
       python -m lightgbm_tpu.profile --perf-card SHAPE [PATH] [--json]

``--perf-card SHAPE [PATH]`` does no training either: it prints the
roofline report card (achieved-fraction-of-peak + bound category,
:mod:`lightgbm_tpu.telemetry.perfmodel`) for one bench shape from an
EXISTING phase-snapshot file or directory (``BENCH_r*_phases.json`` /
``BENCH_phases.json`` / a ``phases_out=`` snapshot from this CLI).

``--merge DIR`` does no training: it merges the rank-suffixed Chrome
traces a multihost run left in DIR (``telemetry_out=`` writes
``out.rN.json`` per rank) into ONE Perfetto-loadable
``merged.trace.json`` with rank-tagged pids, aligning the per-rank host
clocks via the recorded collective barrier spans
(:mod:`lightgbm_tpu.telemetry.merge`). ``--json`` prints the merge
summary as JSON instead of text.

``--shape`` (or ``shape=NAME``) picks the benchmark workload the bench
suite also trains: ``higgs`` (default), ``expo`` (EFB-bundled one-hot —
the bundle fast-path attribution target), ``allstate`` (sparse wide
one-hot), ``yahoo`` / ``msltr`` (lambdarank). Extra ``key=value`` tokens
are passed through as training params (e.g. ``tree_learner=data
num_leaves=511``), except:

  * ``phases_out=PATH`` — write a BENCH_phases.json-style telemetry
    category/scope snapshot for the traced run, keyed by the shape name,
    so the bench's phase breakdown reproduces without the full bench;
  * ``xplane=0`` — skip the device xplane trace (host spans + phase
    snapshot only; the CI smoke test runs this on CPU).

The host-side span registry runs in TRACE mode alongside, so
``telemetry_out=<path>`` also writes the Chrome-trace + metrics files.
"""
from __future__ import annotations

import json
import sys
import time

SHAPE_DEFAULT_ROWS = {"higgs": 2_000_000, "expo": 2_000_000,
                      "allstate": 500_000, "yahoo": 473_134,
                      "msltr": 1_000_000}


def _make_shape(shape: str, rows: int):
    """(X, y, group_or_None, objective) for one bench shape."""
    from lightgbm_tpu.data.synth import (make_allstate_like,
                                         make_expo_like, make_higgs_like,
                                         make_ltr_like, make_yahoo_like)
    if shape == "higgs":
        X, y = make_higgs_like(rows)
        return X, y, None, "binary"
    if shape == "expo":
        X, y = make_expo_like(rows)
        return X, y, None, "binary"
    if shape == "allstate":
        X, y = make_allstate_like(rows)
        return X, y, None, "binary"
    if shape == "yahoo":
        X, y, g = make_yahoo_like(rows)
        return X, y, g, "lambdarank"
    if shape == "msltr":
        X, y, g = make_ltr_like(rows)
        return X, y, g, "lambdarank"
    raise SystemExit("unknown --shape %r (expected higgs|expo|allstate|"
                     "yahoo|msltr)" % shape)


def _phase_stats(events, work=None):
    """Shared snapshot layout + roofline-card stamping; the path
    counters ride along so fast-path engagement stays visible."""
    from lightgbm_tpu.telemetry import perfmodel
    return perfmodel.phase_snapshot(work=work, include_counters=True)


def _main_perf_card(argv) -> int:
    """--perf-card SHAPE [PATH] [--json]: the roofline report card for
    one bench shape from an EXISTING phase-snapshot file (or a directory
    holding one) — no training, no re-run, no accelerator needed. PATH
    defaults to ./BENCH_phases.json; a directory picks the newest
    ``BENCH_r*_phases.json`` (falling back to ``BENCH_phases.json``).
    The device profile comes from the attached accelerator or the
    ``LGBTPU_DEVICE_PROFILE`` override (telemetry/devices.py)."""
    import os

    from lightgbm_tpu.telemetry import perfmodel
    i = argv.index("--perf-card")
    if i + 1 >= len(argv) or argv[i + 1].startswith("-"):
        print("--perf-card needs a shape (higgs|expo|allstate|yahoo|"
              "msltr)", file=sys.stderr)
        return 2
    shape = argv[i + 1].lower()
    rest = [a for a in argv[i + 2:] if not a.startswith("-")]
    path = rest[0] if rest else "."
    if os.path.isdir(path):
        found = perfmodel.find_phase_snapshot(path)
        if found is None:
            print("no BENCH_r*_phases.json / BENCH_phases.json in %s"
                  % path, file=sys.stderr)
            return 2
        path = found
    try:
        with open(path, "r", encoding="utf-8") as f:
            snaps = json.load(f)
    except (OSError, ValueError) as exc:
        print("cannot read phase snapshot %s: %s" % (path, exc),
              file=sys.stderr)
        return 2
    if not isinstance(snaps, dict):
        print("phase snapshot %s is not a JSON object (got %s)"
              % (path, type(snaps).__name__), file=sys.stderr)
        return 2
    # the snapshot is keyed by bench phase name; find the one that maps
    # to the requested shape (bench: higgs/ltr/expo/... ; profile CLI:
    # the shape name itself)
    snap = None
    for phase_key, shape_name in perfmodel.PHASE_SHAPES.items():
        if shape_name == shape and isinstance(snaps.get(phase_key),
                                              dict):
            snap = snaps[phase_key]
            break
    if snap is None:
        print("no phase in %s maps to shape %r (have: %s)"
              % (path, shape, ", ".join(sorted(snaps))),
              file=sys.stderr)
        return 2
    card = perfmodel.report_card(snap, shape)
    if "--json" in argv:
        print(json.dumps(card.to_dict(), sort_keys=True))
    else:
        print(perfmodel.render_cards([card]))
        print("  (snapshot: %s)" % path)
    return 0


def _main_merge(argv) -> int:
    """--merge DIR [--run NAME] [--out PATH] [--json]: no jax import,
    no training. ``--run`` picks one run's rank files by their trace
    basename when the directory mixes several runs (the no-flag default
    still refuses a mixed directory loudly)."""
    from lightgbm_tpu.telemetry import merge as trace_merge
    i = argv.index("--merge")
    if i + 1 >= len(argv):
        print("--merge needs a directory of rank traces", file=sys.stderr)
        return 2
    directory = argv[i + 1]
    out = None
    if "--out" in argv:
        j = argv.index("--out")
        if j + 1 >= len(argv):
            print("--out needs a path", file=sys.stderr)
            return 2
        out = argv[j + 1]
    run = None
    if "--run" in argv:
        j = argv.index("--run")
        if j + 1 >= len(argv):
            print("--run needs a trace basename (run fingerprint)",
                  file=sys.stderr)
            return 2
        run = argv[j + 1]
    try:
        summary = trace_merge.merge_dir(directory, out, run=run)
    except (trace_merge.MergeError, OSError) as exc:
        print("merge failed: %s" % exc, file=sys.stderr)
        return 2
    if "--json" in argv:
        print(json.dumps(summary, sort_keys=True))
    else:
        print("merged %d rank(s) -> %s (%d events)"
              % (len(summary["ranks"]), summary["out"],
                 summary["events"]))
        for r in summary["ranks"]:
            print("  rank %d: clock offset %+.1fus, %d barrier span(s)"
                  % (r, summary["clock_offsets_us"][str(r)],
                     summary["barrier_spans"][r]))
        if summary["dropped_events"]:
            print("  !! %d trace event(s) were dropped at record time "
                  "across ranks (timelines truncated)"
                  % summary["dropped_events"])
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if any(a in ("-h", "--help") for a in argv):
        print(__doc__)
        return 0
    if "--merge" in argv:
        return _main_merge(argv)
    if "--perf-card" in argv:
        return _main_perf_card(argv)
    shape = "higgs"
    if "--shape" in argv:
        i = argv.index("--shape")
        if i + 1 >= len(argv):
            print("--shape needs a value (higgs|expo|allstate|yahoo|"
                  "msltr)", file=sys.stderr)
            return 2
        shape = argv[i + 1]
        del argv[i:i + 2]
    pos = [a for a in argv if "=" not in a]
    kv = [a for a in argv if "=" in a]

    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import kv2map
    from lightgbm_tpu.telemetry import events, maybe_export, xplane

    # objective comes from the SHAPE (lambdarank for the LTR ones) unless
    # the caller overrides it via key=value
    params = {"num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none"}
    params.update(kv2map(kv))
    shape = str(params.pop("shape", shape)).lower()
    rows = int(pos[0]) if len(pos) > 0 else SHAPE_DEFAULT_ROWS.get(
        shape, 2_000_000)
    iters = int(pos[1]) if len(pos) > 1 else 16
    out = params.pop("telemetry_out", None)
    phases_out = params.pop("phases_out", None)
    use_xplane = str(params.pop("xplane", "1")).lower() not in ("0",
                                                                "false")
    # api-source enable, not configure(): config-driven enablement is scoped
    # to the train that asked for it, so the default-params warmup/traced
    # trains below would flip a configure("trace") back off
    events.enable("trace")
    if out:
        events.set_out_path(out)

    X, y, group, obj = _make_shape(shape, rows)
    params.setdefault("objective", obj)
    ds = lgb.Dataset(X, y, group=group) if group is not None \
        else lgb.Dataset(X, y)
    ds.construct()
    n_rows = ds._inner.num_data
    # warmup/compile outside the trace window (compiles are one-time costs)
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm

    events.reset()
    import contextlib
    tracer = xplane.collect_trace() if use_xplane else None
    with (tracer if tracer is not None else contextlib.nullcontext()) \
            as tdir:
        t0 = time.time()
        booster = lgb.train(dict(params), ds, iters, verbose_eval=False)
        booster._booster._materialize_pending()
        jax.block_until_ready(booster._booster.train_score.score_device(0))
        wall = time.time() - t0
    print("shape=%s wall=%.3fs rows=%d iters=%d -> %.2f Mri/s"
          % (shape, wall, n_rows, iters, n_rows * iters / wall / 1e6))

    if phases_out:
        # the bench's BENCH_phases.json layout, keyed by shape, plus the
        # path counters (persist_scan_trees vs v1_grow_trees) so fast-path
        # engagement is visible next to the attribution
        try:
            nl = int(params.get("num_leaves", 255))
        except (TypeError, ValueError):
            nl = 255
        with open(phases_out, "w") as f:
            json.dump({shape: _phase_stats(
                events, work={"phase": shape, "rows": n_rows,
                              "iters": iters, "num_leaves": nl})},
                f, indent=1, sort_keys=True)
        print("telemetry phase snapshot written to %s" % phases_out,
              file=sys.stderr)

    if use_xplane:
        try:
            planes = xplane.parse_xplane_dir(tdir)
        except ImportError as exc:
            print("xplane proto bindings unavailable (%s); raw trace left "
                  "in %s" % (exc, tdir), file=sys.stderr)
            return 1
        print(xplane.format_device_report(planes, iters=iters))
    written = maybe_export(out) if out else None
    if written:
        print("host-side spans: %s ; metrics: %s" % written, file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
