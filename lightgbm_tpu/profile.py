"""`python -m lightgbm_tpu.profile` — op-level device profile of training.

Traces N boosting iterations on the real chip with the jax profiler, then
prints device time per XLA op name via the reusable xplane parser
(:mod:`lightgbm_tpu.telemetry.xplane`). The old top-level ``prof_trace.py``
dev script is now a thin wrapper over this entry point.

Usage: python -m lightgbm_tpu.profile [rows] [iters] [key=value ...]

Extra `key=value` tokens are passed through as training params
(e.g. ``tree_learner=data num_leaves=511``). The host-side span registry
runs in TRACE mode alongside, so ``telemetry_out=<path>`` also writes the
Chrome-trace + metrics files for the same run.
"""
from __future__ import annotations

import sys
import time


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if any(a in ("-h", "--help") for a in argv):
        print(__doc__)
        return 0
    pos = [a for a in argv if "=" not in a]
    kv = [a for a in argv if "=" in a]
    rows = int(pos[0]) if len(pos) > 0 else 2_000_000
    iters = int(pos[1]) if len(pos) > 1 else 16

    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import kv2map
    from lightgbm_tpu.data.synth import make_higgs_like
    from lightgbm_tpu.telemetry import events, maybe_export, xplane

    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none"}
    params.update(kv2map(kv))
    out = params.pop("telemetry_out", None)
    # api-source enable, not configure(): config-driven enablement is scoped
    # to the train that asked for it, so the default-params warmup/traced
    # trains below would flip a configure("trace") back off
    events.enable("trace")
    if out:
        events.set_out_path(out)

    X, y = make_higgs_like(rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    # warmup/compile outside the trace window (compiles are one-time costs)
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm

    events.reset()
    with xplane.collect_trace() as tdir:
        t0 = time.time()
        booster = lgb.train(dict(params), ds, iters, verbose_eval=False)
        booster._booster._materialize_pending()
        jax.block_until_ready(booster._booster.train_score.score_device(0))
        wall = time.time() - t0
    print("wall=%.3fs rows=%d iters=%d -> %.2f Mri/s"
          % (wall, rows, iters, rows * iters / wall / 1e6))

    try:
        planes = xplane.parse_xplane_dir(tdir)
    except ImportError as exc:
        print("xplane proto bindings unavailable (%s); raw trace left in %s"
              % (exc, tdir), file=sys.stderr)
        return 1
    print(xplane.format_device_report(planes, iters=iters))
    written = maybe_export(out) if out else None
    if written:
        print("host-side spans: %s ; metrics: %s" % written, file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
