"""Command-line application: train / predict with .conf files.

TPU-native rebuild of src/main.cpp + src/application/application.cpp: parse
`key=value` args and an optional `config=<file>` (CLI wins over file,
application.cpp:49-82), dispatch on `task` (train :164-210, predict
:212-240, refit via GBDT::RefitTree; convert_model reports unimplemented).
Usage is CLI-compatible with the reference:

    python -m lightgbm_tpu config=train.conf [key=value ...]
"""
from __future__ import annotations

import sys

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .data.loader import load_text_file
from .engine import train as engine_train
from .utils.log import LightGBMError, Log


class Application:
    def __init__(self, argv):
        self.config = Config.from_cli_args(argv)
        if self.config.data == "" and self.config.task in ("train", "refit"):
            Log.fatal("No training/prediction data, application quit")

    def run(self):
        task = self.config.task
        if task == "train":
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task in ("refit", "refit_tree"):
            self.refit()
        else:
            Log.fatal("Unknown task type %s" % task)

    # ------------------------------------------------------------------
    def convert_model(self):
        """convert_model task (application.cpp ConvertModel +
        GBDT::SaveModelToIfElse): model text -> standalone C++ source."""
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("Need input_model for convert_model task")
        booster = Booster(model_file=cfg.input_model)
        out = cfg.convert_model or "gbdt_prediction.cpp"
        with open(out, "w") as f:
            f.write(booster._booster.model_to_if_else())
        Log.info("Finished converting; C++ code saved to %s" % out)

    # ------------------------------------------------------------------
    def refit(self):
        """Refit task (application.cpp refit path + GBDT::RefitTree)."""
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("Need input_model for refit task")
        booster = Booster(model_file=cfg.input_model, params=cfg.to_dict())
        loaded = load_text_file(cfg.data, cfg)
        new_b = booster.refit(loaded.X, loaded.label,
                              decay_rate=cfg.refit_decay_rate)
        new_b.save_model(cfg.output_model)
        Log.info("Finished refit; model saved to %s" % cfg.output_model)

    # ------------------------------------------------------------------
    def train(self):
        cfg = self.config
        if int(cfg.num_machines) > 1:
            return self.train_distributed()
        params = cfg.to_dict()
        # path Datasets get the binary cache (save_binary/<data>.bin) and
        # two_round streaming through Dataset._construct_from_path
        train_set = Dataset(cfg.data, params=params)
        valid_sets = []
        valid_names = []
        for i, vfile in enumerate(cfg.valid):
            valid_sets.append(Dataset(vfile, reference=train_set,
                                      params=params))
            valid_names.append("valid_%d" % i if len(cfg.valid) > 1
                               else "valid_1")
        booster = engine_train(
            params, train_set,
            num_boost_round=cfg.num_iterations,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            early_stopping_rounds=(cfg.early_stopping_round or None),
            verbose_eval=True)
        booster.save_model(cfg.output_model)
        Log.info("Finished training; model saved to %s" % cfg.output_model)

    # ------------------------------------------------------------------
    def train_distributed(self):
        """num_machines > 1: delegate to the engine's distributed path
        (engine._train_distributed via engine.train) so the CLI rides the
        same sharding, collective-retry, and checkpoint/resume wiring as
        the Python API — file-backed Datasets load + shard inside
        (engine._distributed_raw handles paths); every rank materializes
        the full model, rank 0 persists it (application.cpp:164-210)."""
        import jax
        cfg = self.config
        params = cfg.to_dict()
        train_set = Dataset(cfg.data, params=params)
        valid_sets = [Dataset(v, params=params) for v in cfg.valid]
        booster = engine_train(
            params, train_set,
            num_boost_round=cfg.num_iterations,
            valid_sets=valid_sets or None,
            early_stopping_rounds=(cfg.early_stopping_round or None),
            verbose_eval=True)
        if jax.process_index() == 0:
            booster.save_model(cfg.output_model)
            Log.info("Finished distributed training; model saved to %s"
                     % cfg.output_model)

    # ------------------------------------------------------------------
    def predict(self):
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("Need input_model for predict task")
        booster = Booster(model_file=cfg.input_model,
                          params=cfg.to_dict())
        loaded = load_text_file(cfg.data, cfg)
        num_iteration = (cfg.num_iteration_predict
                         if cfg.num_iteration_predict > 0 else None)
        if cfg.predict_leaf_index:
            result = booster.predict(loaded.X, pred_leaf=True,
                                     num_iteration=num_iteration)
        elif cfg.predict_contrib:
            result = booster.predict(loaded.X, pred_contrib=True,
                                     num_iteration=num_iteration)
        elif cfg.predict_device == "tpu" and not cfg.pred_early_stop:
            # (pred_early_stop is host-only; that combination falls through
            # to booster.predict, which logs the fallback — CLI and Python
            # API behave identically)
            # score predictions ride the bucketed batch-serving runtime:
            # bounded recompiles, chunked device memory, mesh fan-out
            from .predict import BatchServer, EnsembleCompileError
            Log.info("Serving predictions on the device runtime "
                     "(predict_device=tpu%s)"
                     % (", async" if cfg.tpu_serve_async else ""))
            try:
                if cfg.tpu_serve_async:
                    # the continuous-batching path: one CLI request is a
                    # single admitted batch, but the server chunks,
                    # coalesces and shards identically to a live
                    # deployment — tpu_serve_quant rides the registry's
                    # certified load seam
                    from .serving import AsyncBatchServer, ModelRegistry
                    registry = ModelRegistry(
                        dtype=cfg.tpu_predict_dtype,
                        min_rows=cfg.tpu_predict_min_batch)
                    registry.load("cli", booster=booster,
                                  quant=cfg.tpu_serve_quant)
                    with AsyncBatchServer(
                            registry,
                            min_batch=cfg.tpu_predict_min_batch,
                            max_batch=cfg.tpu_predict_max_batch,
                            max_wait_ms=cfg.tpu_serve_max_wait_ms
                            ) as server:
                        result = server.predict(
                            loaded.X, raw_score=cfg.predict_raw_score)
                else:
                    server = BatchServer(
                        booster._booster.device_predictor(
                            0, num_iteration if num_iteration else -1),
                        min_batch=cfg.tpu_predict_min_batch,
                        max_batch=cfg.tpu_predict_max_batch)
                    result = server.predict(
                        loaded.X, raw_score=cfg.predict_raw_score)
            except EnsembleCompileError as exc:
                Log.warning("predict_device=tpu: %s; falling back to the "
                            "host predictor" % exc)
                result = booster.predict(loaded.X,
                                         raw_score=cfg.predict_raw_score,
                                         num_iteration=num_iteration)
        else:
            result = booster.predict(loaded.X,
                                     raw_score=cfg.predict_raw_score,
                                     num_iteration=num_iteration)
        result = np.asarray(result)
        if result.ndim == 1:
            result = result.reshape(-1, 1)
        np.savetxt(cfg.output_result, result, fmt="%.18g", delimiter="\t")
        Log.info("Finished prediction; results saved to %s"
                 % cfg.output_result)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("Usage: python -m lightgbm_tpu config=<conf> [key=value ...]")
        return 1
    try:
        Application(argv).run()
    except LightGBMError as e:
        Log.warning("Met Exceptions: %s" % e)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
