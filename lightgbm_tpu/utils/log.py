"""Logging with LightGBM-style levels (Fatal/Warning/Info/Debug).

TPU-native rebuild of the reference logger (include/LightGBM/utils/log.h:61-100):
a tiny static-level logger with a pluggable callback, used by the whole framework
and redirectable by language bindings.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(Exception):
    """Raised on fatal errors (reference: Log::Fatal throws std::runtime_error)."""


class Log:
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2

    _level: int = INFO
    _callback: Optional[Callable[[str], None]] = None

    @classmethod
    def reset_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def reset_callback(cls, callback: Optional[Callable[[str], None]]) -> None:
        cls._callback = callback

    @classmethod
    def _write(cls, level_str: str, msg: str) -> None:
        text = "[LightGBM-TPU] [%s] %s\n" % (level_str, msg)
        if cls._callback is not None:
            cls._callback(text)
        else:
            sys.stderr.write(text)
            sys.stderr.flush()

    @classmethod
    def debug(cls, msg: str, *args) -> None:
        if cls._level >= cls.DEBUG:
            cls._write("Debug", msg % args if args else msg)

    @classmethod
    def info(cls, msg: str, *args) -> None:
        if cls._level >= cls.INFO:
            cls._write("Info", msg % args if args else msg)

    @classmethod
    def warning(cls, msg: str, *args) -> None:
        if cls._level >= cls.WARNING:
            cls._write("Warning", msg % args if args else msg)

    @classmethod
    def fatal(cls, msg: str, *args) -> None:
        text = msg % args if args else msg
        cls._write("Fatal", text)
        raise LightGBMError(text)
