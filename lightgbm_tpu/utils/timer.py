"""Named-scope wall-clock accounting: where does training time go?

TPU-native analog of the reference's compile-time-gated ``Timer`` /
``FunctionTimer`` pair (include/LightGBM/utils/common.h:1026-1105, enabled
with -DUSE_TIMETAG): one process-global accumulator of named durations,
RAII-style scopes on the hot functions, a sorted report at exit.

Differences driven by the JAX execution model:
  * dispatch is async — a scope that merely *launches* a jitted program
    measures launch cost, not device time. Scopes that want device time
    must block (``sync=True`` passes the scope's result through
    ``jax.block_until_ready``). The growers keep async pipelining, so by
    default the report shows the honest host-side decomposition (binning,
    gradient compute, launch, materialize/transfer, eval) and one "device
    wait" bucket where the pipeline actually blocks.
  * enablement is a runtime env var (``LIGHTGBM_TPU_TIMETAG=1``) or
    ``timer.enable()``, not a compile flag.

Report via ``lightgbm_tpu.utils.timer.print_report()`` (also auto-printed
at interpreter exit when enabled, like the reference's global_timer dtor).
"""
from __future__ import annotations

import atexit
import contextlib
import functools
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Tuple

_lock = threading.Lock()
_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)
_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
_stack = threading.local()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _lock:
        _acc.clear()
        _cnt.clear()


def add(name: str, seconds: float) -> None:
    with _lock:
        _acc[name] += seconds
        _cnt[name] += 1


@contextlib.contextmanager
def scope(name: str, sync_value=None):
    """Accumulate the wall time of the enclosed block under `name`.

    When `sync_value` is a callable, it is invoked on exit and its result
    passed to jax.block_until_ready before the clock stops — use for
    scopes whose cost is a device computation.
    """
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if sync_value is not None:
            try:
                import jax
                jax.block_until_ready(sync_value())
            except Exception:
                pass
        add(name, time.perf_counter() - t0)


def timed(name: str) -> Callable:
    """Decorator form (the FunctionTimer analog)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrap(*a, **k):
            if not _enabled:
                return fn(*a, **k)
            t0 = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                add(name, time.perf_counter() - t0)
        return wrap
    return deco


def snapshot() -> Dict[str, Tuple[float, int]]:
    with _lock:
        return {k: (_acc[k], _cnt[k]) for k in _acc}


def print_report(out=None) -> None:
    """Sorted-by-time table, like Timer::Print (common.h:1059)."""
    snap = snapshot()
    if not snap:
        return
    import sys
    out = out or sys.stderr
    total = sum(v for v, _ in snap.values())
    print("[LightGBM-TPU] [Info] time-tag report "
          "(host wall per named scope; async launches exclude device time)",
          file=out)
    width = max(len(k) for k in snap)
    for name, (sec, n) in sorted(snap.items(), key=lambda kv: -kv[1][0]):
        print("  %-*s %10.3fs  x%-7d %5.1f%%"
              % (width, name, sec, n, 100.0 * sec / max(total, 1e-12)),
              file=out)
    print("  %-*s %10.3fs" % (width, "(sum)", total), file=out)


@atexit.register
def _report_at_exit() -> None:  # pragma: no cover - exit path
    if _enabled:
        print_report()
