"""Named-scope wall-clock accounting — thin aliases over the telemetry
subsystem.

This module used to own the process-global accumulator (the TPU-native
analog of the reference's compile-time-gated ``Timer`` / ``FunctionTimer``
pair, include/LightGBM/utils/common.h:1026-1105, -DUSE_TIMETAG). That
registry now lives in :mod:`lightgbm_tpu.telemetry.events` — with span
categories, a trace-event timeline, and Chrome-trace/JSONL export — and
this module keeps the original call surface (``timer.timed``,
``timer.scope``, ``timer.enable``, ``timer.print_report``,
``LIGHTGBM_TPU_TIMETAG=1``, the atexit report) as pass-throughs so
existing call sites keep working unchanged.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

from ..telemetry import events as _ev
from ..telemetry.export import print_report  # noqa: F401  (re-export)


def enable() -> None:
    _ev.enable("timers")


def disable() -> None:
    _ev.disable()


def enabled() -> bool:
    return _ev.enabled()


def reset() -> None:
    _ev.reset()


def add(name: str, seconds: float) -> None:
    _ev.add(name, seconds)


def scope(name: str, sync_value=None, category: str = "misc"):
    """Accumulate the wall time of the enclosed block under `name` (see
    telemetry.events.scope; `sync_value` blocks on a device value before
    the clock stops)."""
    return _ev.scope(name, category=category, sync_value=sync_value)


def timed(name: str, category: str = "misc") -> Callable:
    """Decorator form (the FunctionTimer analog)."""
    return _ev.timed(name, category=category)


def snapshot() -> Dict[str, Tuple[float, int]]:
    return _ev.snapshot()
