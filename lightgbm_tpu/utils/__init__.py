"""lightgbm_tpu.utils"""
