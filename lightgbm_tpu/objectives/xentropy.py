"""Cross-entropy objectives for continuous labels in [0, 1].

TPU-native rebuild of src/objective/xentropy_objective.hpp:44-262: plain
cross-entropy (logistic link, :77-96) and the weight-lambda
parameterization (:185-213) as vectorized jax functions.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..utils.log import Log
from .base import K_EPSILON, ObjectiveFunction, register


@register
class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0.0 or self.label.max() > 1.0:
            Log.fatal("[%s]: label outside [0, 1]" % self.name)
        if self.weight is not None:
            if self.weight.min() < 0.0:
                Log.fatal("[%s]: at least one weight is negative" % self.name)
            if self.weight.sum() == 0.0:
                Log.fatal("[%s]: sum of weights is zero" % self.name)

    def grad_fn(self):
        def fn(score, label, weight):
            z = 1.0 / (1.0 + jnp.exp(-score))
            g = z - label
            h = z * (1.0 - z)
            if weight is None:
                return g, h
            return g * weight, h * weight
        return fn

    def boost_from_score(self, class_id):
        if self.weight is not None:
            pavg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        initscore = float(np.log(pavg / (1.0 - pavg)))
        Log.info("[%s]: pavg = %f -> initscore = %f"
                 % (self.name, pavg, initscore))
        return initscore

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


@register
class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0.0 or self.label.max() > 1.0:
            Log.fatal("[%s]: label outside [0, 1]" % self.name)
        if self.weight is not None and self.weight.min() <= 0.0:
            Log.fatal("[%s]: at least one weight is non-positive" % self.name)

    def grad_fn(self):
        def fn(score, label, weight):
            if weight is None:
                z = 1.0 / (1.0 + jnp.exp(-score))
                return z - label, z * (1.0 - z)
            epf = jnp.exp(score)
            hhat = jnp.log1p(epf)
            z = 1.0 - jnp.exp(-weight * hhat)
            enf = 1.0 / epf
            g = (1.0 - label / z) * weight / (1.0 + enf)
            c = 1.0 / (1.0 - z)
            d = 1.0 + epf
            a = weight * epf / (d * d)
            d = c - 1.0
            b = (c / (d * d)) * (1.0 + weight * epf - c)
            return g, a * (1.0 + label * b)
        return fn

    def boost_from_score(self, class_id):
        if self.weight is not None:
            havg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            havg = float(np.mean(self.label))
        initscore = float(np.log(np.exp(havg) - 1.0))
        Log.info("[%s]: havg = %f -> initscore = %f"
                 % (self.name, havg, initscore))
        return initscore

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))
