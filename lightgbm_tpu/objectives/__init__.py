"""Objective functions (src/objective/ rebuild, TPU-native)."""
from .base import (ObjectiveFunction, create_objective,
                   parse_objective_string, percentile, weighted_percentile)
from . import binary, multiclass, rank, regression, xentropy  # noqa: F401

__all__ = ["ObjectiveFunction", "create_objective", "parse_objective_string",
           "percentile", "weighted_percentile"]
