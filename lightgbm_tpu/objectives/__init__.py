"""lightgbm_tpu.objectives"""
