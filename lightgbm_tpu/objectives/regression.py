"""Regression objectives.

TPU-native rebuild of src/objective/regression_objective.hpp. Each objective's
per-row math is a pure jax function (vectorized over the score vector, the TPU
equivalent of the reference's OpenMP loops at e.g. regression_objective.hpp:126,
217, 310, 365, 437, 496, 594, 692, 730); BoostFromScore and the L1-family
weighted-median leaf renewal reproduce the reference percentile semantics
exactly (PercentileFun/WeightedPercentileFun, :18-90).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..utils.log import Log
from .base import ObjectiveFunction, percentile, register, weighted_percentile


def _sign(x):
    # dtype-following ±1/0 (NaN -> 0, unlike jnp.sign): a dtype-defaulted
    # select is f64 under x64 and would silently widen f32 gradient math
    return jnp.where(x > 0, 1.0, jnp.where(x < 0, -1.0, jnp.zeros_like(x)))


@register
class RegressionL2Loss(ObjectiveFunction):
    """L2 loss (regression_objective.hpp:93-199)."""

    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lab = self.label
            self.label = (np.sign(lab) * np.sqrt(np.fabs(lab))).astype(np.float32)

    def grad_fn(self):
        def fn(score, label, weight):
            diff = score - label
            if weight is None:
                return diff, jnp.ones_like(diff)
            return diff * weight, weight
        return fn

    def payload_grad_fn(self):
        # weights ride the payload and multiply AFTER this fn
        # (grow_persist._apply_weight); sqrt needs the transformed label
        if self.sqrt:
            return None
        base = self.grad_fn()

        def fn(score, label):
            return base(score, label, None)
        return fn

    @property
    def is_constant_hessian(self):
        return self.weight is None

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return float(np.sum(self.label * self.weight) / np.sum(self.weight))
        return float(np.mean(self.label))

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return self.name + (" sqrt" if self.sqrt else "")


@register
class RegressionL1Loss(RegressionL2Loss):
    """L1 loss with weighted-median leaf renewal (regression_objective.hpp:204)."""

    name = "regression_l1"
    _alpha = 0.5

    def grad_fn(self):
        def fn(score, label, weight):
            diff = score - label
            g = _sign(diff)
            if weight is None:
                return g, jnp.ones_like(g)
            return g * weight, weight
        return fn

    @property
    def is_constant_hessian(self):
        return self.weight is None

    @property
    def is_renew_tree_output(self):
        return True

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, self._alpha)
        return percentile(self.label, self._alpha)

    def renew_tree_output(self, pred_in_leaf, label_in_leaf, weight_in_leaf):
        residual = label_in_leaf.astype(np.float64) - pred_in_leaf
        if len(residual) == 0:
            return 0.0
        if weight_in_leaf is None:
            return percentile(residual, self._alpha)
        return weighted_percentile(residual, weight_in_leaf, self._alpha)

    def convert_output(self, raw):
        return raw

    def to_string(self):
        return self.name


@register
class RegressionHuberLoss(RegressionL2Loss):
    """Huber loss (regression_objective.hpp:290)."""

    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        if self.sqrt:
            Log.warning("Cannot use sqrt transform in %s Regression, "
                        "will auto disable it" % self.name)
            self.sqrt = False

    def grad_fn(self):
        a = self.alpha

        def fn(score, label, weight):
            diff = score - label
            g = jnp.where(jnp.abs(diff) <= a, diff, _sign(diff) * a)
            if weight is None:
                return g, jnp.ones_like(g)
            return g * weight, weight
        return fn

    @property
    def is_constant_hessian(self):
        return False

    def to_string(self):
        return self.name


@register
class RegressionFairLoss(RegressionL2Loss):
    """Fair loss (regression_objective.hpp:352)."""

    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def grad_fn(self):
        c = self.c

        def fn(score, label, weight):
            x = score - label
            denom = jnp.abs(x) + c
            g = c * x / denom
            h = c * c / (denom * denom)
            if weight is None:
                return g, h
            return g * weight, h * weight
        return fn

    @property
    def is_constant_hessian(self):
        return False

    def to_string(self):
        return self.name


@register
class RegressionPoissonLoss(RegressionL2Loss):
    """Poisson regression: score is log-intensity (regression_objective.hpp:399)."""

    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)
        if self.sqrt:
            Log.warning("Cannot use sqrt transform in %s Regression, "
                        "will auto disable it" % self.name)
            self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.min(self.label) < 0.0:
            Log.fatal("[%s]: at least one target label is negative" % self.name)
        if np.sum(self.label) == 0.0:
            Log.fatal("[%s]: sum of labels is zero" % self.name)

    def grad_fn(self):
        mds = self.max_delta_step

        def fn(score, label, weight):
            g = jnp.exp(score) - label
            h = jnp.exp(score + mds)
            if weight is None:
                return g, h
            return g * weight, h * weight
        return fn

    @property
    def is_constant_hessian(self):
        return False

    def boost_from_score(self, class_id):
        mean = RegressionL2Loss.boost_from_score(self, class_id)
        # Common::SafeLog
        return float(np.log(mean)) if mean > 0 else -np.inf

    def convert_output(self, raw):
        return np.exp(raw)

    def to_string(self):
        return self.name


@register
class RegressionQuantileLoss(RegressionL2Loss):
    """Quantile (pinball) loss (regression_objective.hpp:479)."""

    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = np.float32(config.alpha)
        if not (0 < self.alpha < 1):
            Log.fatal("Quantile alpha should be in (0, 1)")

    def grad_fn(self):
        a = np.float32(self.alpha)

        def fn(score, label, weight):
            delta = (score - label).astype(jnp.float32)
            g = jnp.where(delta >= 0, 1.0 - a, -a)
            if weight is None:
                return g, jnp.ones_like(g)
            return g * weight, weight
        return fn

    @property
    def is_constant_hessian(self):
        return self.weight is None

    @property
    def is_renew_tree_output(self):
        return True

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, float(self.alpha))
        return percentile(self.label, float(self.alpha))

    def renew_tree_output(self, pred_in_leaf, label_in_leaf, weight_in_leaf):
        residual = label_in_leaf.astype(np.float64) - pred_in_leaf
        if len(residual) == 0:
            return 0.0
        if weight_in_leaf is None:
            return percentile(residual, float(self.alpha))
        return weighted_percentile(residual, weight_in_leaf, float(self.alpha))

    def to_string(self):
        return self.name


@register
class RegressionMAPELoss(RegressionL1Loss):
    """MAPE loss (regression_objective.hpp:577)."""

    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(np.fabs(self.label) < 1):
            Log.warning("Met 'abs(label) < 1', will convert them to '1' in "
                        "MAPE objective and metric")
        lw = 1.0 / np.maximum(1.0, np.fabs(self.label))
        if self.weight is not None:
            lw = lw * self.weight
        self.label_weight = lw.astype(np.float32)

    def grad_fn(self):
        def fn(score, label, weight, label_weight):
            diff = score - label
            g = _sign(diff) * label_weight
            if weight is None:
                return g, jnp.ones_like(g)
            return g, weight
        return fn

    def payload_grad_fn(self):
        # the label-only payload contract cannot carry label_weight
        # (and the inherited L2 wrapper would call the 4-arg grad_fn
        # with 3 args — a trace-time crash); MAPE's device capability
        # is the row-order kernel, which device_gradients picks up
        # through grad_fn/_grad_args
        return None

    def _grad_args(self):
        label, weight = super()._grad_args()
        return (label, weight, jnp.asarray(self.label_weight))

    @property
    def is_constant_hessian(self):
        return True

    def boost_from_score(self, class_id):
        return weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, pred_in_leaf, label_in_leaf, weight_in_leaf):
        # weight used is label_weight (reference :655-672); the caller passes
        # it via weight_in_leaf (GBDT renews with objective-provided weights)
        residual = label_in_leaf.astype(np.float64) - pred_in_leaf
        if len(residual) == 0:
            return 0.0
        return weighted_percentile(residual, weight_in_leaf, 0.5)

    def to_string(self):
        return self.name


@register
class RegressionGammaLoss(RegressionPoissonLoss):
    """Gamma regression (regression_objective.hpp:676)."""

    name = "gamma"

    def grad_fn(self):
        def fn(score, label, weight):
            exps = jnp.exp(score)
            if weight is None:
                return 1.0 - label / exps, label / exps
            # reference :700-702 applies weight inside the subtraction
            return 1.0 - label / exps * weight, label / exps * weight
        return fn

    def to_string(self):
        return self.name


@register
class RegressionTweedieLoss(RegressionPoissonLoss):
    """Tweedie regression (regression_objective.hpp:711)."""

    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def grad_fn(self):
        rho = self.rho

        def fn(score, label, weight):
            e1 = jnp.exp((1 - rho) * score)
            e2 = jnp.exp((2 - rho) * score)
            g = -label * e1 + e2
            h = -label * (1 - rho) * e1 + (2 - rho) * e2
            if weight is None:
                return g, h
            return g * weight, h * weight
        return fn

    def to_string(self):
        return self.name
