"""Learning-to-rank objectives: LambdaRank-NDCG and XE-NDCG.

TPU-native rebuild of src/objective/rank_objective.hpp. The reference
parallelizes over queries with OpenMP and walks O(n^2) document pairs per
query (LambdarankNDCG::GetGradientsForOneQuery, rank_objective.hpp:139-232);
here queries are packed into a padded [num_queries, max_len] layout and the
pair loop becomes a vmapped [P, P] pairwise tensor computation, chunked with
lax.map to bound memory. XE-NDCG (rank_objective.hpp:288-352) is O(n) per
query and is expressed with segment sums over the flat row axis — no padding.

Deliberate deviation from the reference (documented for the parity tests):
the 1M-entry sigmoid lookup table (:237-257) is replaced by exact sigmoid
evaluation — on TPU computing exp is cheaper than a 1M-gather, and it is
strictly more accurate. XE-NDCG's per-query Random stream (:305-312) is
reproduced BIT-EXACTLY: the host advances the reference's LCG per query
(RankXENDCG._next_floats) and ships each iteration's draws to the jitted
gradient function, so the golden parity suite matches the reference's
stochastic gradients too.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..metrics.dcg import (cal_max_dcg_at_k, check_label, default_label_gain)
from ..utils.log import Log
from .base import K_EPSILON, ObjectiveFunction, register


def _pack_queries(query_boundaries: np.ndarray):
    """[nq+1] boundaries -> (row_index [Q, P] padded with -1, valid [Q, P])."""
    nq = len(query_boundaries) - 1
    counts = np.diff(query_boundaries)
    P = int(counts.max()) if nq else 1
    idx = np.full((nq, P), -1, dtype=np.int32)
    for q in range(nq):
        c = counts[q]
        idx[q, :c] = np.arange(query_boundaries[q], query_boundaries[q + 1],
                               dtype=np.int32)
    return idx, (idx >= 0)


class RankingObjective(ObjectiveFunction):
    """Base: per-query gradient computation (rank_objective.hpp:25-94)."""

    def __init__(self, config):
        super().__init__(config)
        self.seed = int(config.objective_seed)
        self.query_boundaries = None
        self.num_queries = 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Ranking tasks require query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = metadata.num_queries


@register
class LambdarankNDCG(RankingObjective):
    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        lg = list(config.label_gain)
        self.label_gain = (np.asarray(lg, dtype=np.float64) if lg
                           else default_label_gain())
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero"
                      % self.sigmoid)
        self._chunk = 0     # queries per lax.map step; 0 = size by memory

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check_label(self.label, len(self.label_gain))
        inv = np.zeros(self.num_queries)
        qb = self.query_boundaries
        for q in range(self.num_queries):
            m = cal_max_dcg_at_k(self.truncation_level,
                                 self.label[qb[q]:qb[q + 1]], self.label_gain)
            inv[q] = 1.0 / m if m > 0.0 else 0.0
        self.inverse_max_dcgs = inv
        self._qidx, self._qvalid = _pack_queries(qb)
        # row -> padded position (q*P + offset): the padded [Q, P] lambdas
        # return to row order with one gather (TPU scatters serialize;
        # queries are contiguous row ranges so this map is static)
        P = self._qidx.shape[1]
        counts = np.diff(qb)
        qid = np.repeat(np.arange(self.num_queries, dtype=np.int64), counts)
        self._inv_pos = (qid * P + (np.arange(self.num_data, dtype=np.int64)
                                    - qb[qid])).astype(np.int32)
        # padded per-slot statics for the payload-position gradient mode:
        # labels never change, so the [Q, P] label/gain/weight planes are
        # computed once and only SCORES move per iteration
        safe = np.maximum(self._qidx, 0)
        self._lab_pad = np.where(self._qvalid, self.label[safe], 0.0) \
            .astype(np.float32)
        self._gains_pad = self.label_gain[self._lab_pad.astype(np.int64)] \
            .astype(np.float64)
        self._w_pad = (np.where(self._qvalid, self.weight[safe], 0.0)
                       .astype(np.float32)
                       if self.weight is not None else None)
        if self._chunk <= 0:
            # budget the [chunk, P, P] pairwise intermediates to ~256MB:
            # tiny chunks turn lax.map into hundreds of sequential
            # dispatch-bound steps (a 256-query chunk at P=73 is 5MB of
            # work per step — measured 10x slower than 2 big steps)
            P = max(int(self._qidx.shape[1]), 1)
            self._chunk = max(256, min(self.num_queries,
                                       (256 << 20) // (P * P * 4)))

    def _pairwise_flat(self):
        """Shared pairwise core: fn(s_q [Q, P], l_q, qvalid, inv_max_dcgs,
        gains_q, discounts) -> (lam_flat, hess_flat) over the padded slots
        (chunk-padded queries appended at the end; callers index by padded
        position, which never reaches the pad)."""
        sigmoid = self.sigmoid
        norm = self.norm
        chunk = self._chunk
        # f64 on TPU is emulated op-by-op; the pairwise tensors dominate
        # this objective, so compute them in f32 on accelerators (the
        # reference itself trades exactness here with its 1M-entry sigmoid
        # table, rank_objective.hpp:237-257). CPU keeps f64 for the
        # reference-parity suite.
        import jax as _jax
        ct = (jnp.float64 if _jax.default_backend() == "cpu"
              else jnp.float32)

        def one_query(scores_q, labels_q, valid_q, inv_max_dcg, gains_q,
                      disc_from_rank):
            """Pairwise lambdas of one padded query.

            scores_q/labels_q/valid_q: [P]; returns ([P] lambdas, [P] hess).
            Mirrors rank_objective.hpp:139-232 with masks replacing the
            `continue` conditions.
            """
            P = scores_q.shape[0]
            neg_inf = jnp.asarray(-jnp.inf, scores_q.dtype)
            s = jnp.where(valid_q, scores_q, neg_inf)
            # per-row discount WITHOUT a gather (TPU gathers serialize):
            # sort rows by descending score carrying the row index, then
            # sort back by row index carrying the rank's discount — two
            # payload-carrying sorts replace argsort+argsort+table-gather
            iota = jnp.arange(P, dtype=jnp.int32)
            neg_s, row_of_rank = jax.lax.sort((-s, iota), num_keys=1,
                                              is_stable=True)
            _, disc = jax.lax.sort((row_of_rank, disc_from_rank[:P]),
                                   num_keys=1, is_stable=True)
            n_valid = jnp.sum(valid_q.astype(jnp.int32))
            best_score = -neg_s[0]
            worst_score = -neg_s[jnp.maximum(n_valid - 1, 0)]

            # pairwise [P, P]: i = high row, j = low row
            lab = labels_q.astype(jnp.int32)
            gain = gains_q                        # [P] label gain per row
            d_score = s[:, None] - s[None, :]
            pair_valid = (valid_q[:, None] & valid_q[None, :]
                          & (lab[:, None] > lab[None, :]))
            dcg_gap = gain[:, None] - gain[None, :]
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_pair_ndcg = dcg_gap * paired_disc * inv_max_dcg
            if norm:
                delta_pair_ndcg = jnp.where(
                    best_score != worst_score,
                    delta_pair_ndcg / (0.01 + jnp.abs(d_score)),
                    delta_pair_ndcg)
            p_lambda = 1.0 / (1.0 + jnp.exp(d_score * sigmoid))
            p_hess = p_lambda * (1.0 - p_lambda)
            p_lambda = -sigmoid * delta_pair_ndcg * p_lambda
            p_hess = sigmoid * sigmoid * delta_pair_ndcg * p_hess
            p_lambda = jnp.where(pair_valid, p_lambda, 0.0)
            p_hess = jnp.where(pair_valid, p_hess, 0.0)

            lambdas = jnp.sum(p_lambda, axis=1) - jnp.sum(p_lambda, axis=0)
            hess = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
            sum_lambdas = -2.0 * jnp.sum(p_lambda)
            if norm:
                norm_factor = jnp.where(
                    sum_lambdas > 0,
                    jnp.log2(1 + sum_lambdas) / sum_lambdas, 1.0)
                lambdas = lambdas * norm_factor
                hess = hess * norm_factor
            return lambdas, hess

        def core(s_q, l_q, qvalid, inv_max_dcgs, gains_q, discounts):
            Q, P = s_q.shape
            s_q = s_q.astype(ct)
            gains_q = gains_q.astype(ct)
            inv_max_dcgs = inv_max_dcgs.astype(ct)
            discounts = discounts.astype(ct)

            def chunk_fn(args):
                sq, lq, vq, inv, gq = args
                return jax.vmap(one_query, in_axes=(0, 0, 0, 0, 0, None))(
                    sq, lq, vq, inv, gq, discounts)

            # chunk the query axis to bound the [chunk, P, P] intermediate
            pad_q = (-Q) % chunk
            def padq(x):
                return jnp.pad(x, ((0, pad_q),) + ((0, 0),) * (x.ndim - 1))
            sq, lq, vq, gq = padq(s_q), padq(l_q), padq(qvalid), padq(gains_q)
            inv = jnp.pad(inv_max_dcgs, (0, pad_q))
            nchunks = (Q + pad_q) // chunk
            resh = lambda x: x.reshape((nchunks, chunk) + x.shape[1:])
            lam_c, hes_c = jax.lax.map(
                chunk_fn, (resh(sq), resh(lq), resh(vq), resh(inv), resh(gq)))
            return lam_c.reshape(-1), hes_c.reshape(-1)
        return core

    def grad_fn(self):
        core = self._pairwise_flat()

        def fn(score, label, weight, qidx, qvalid, inv_max_dcgs, label_gain,
               discounts, inv_pos):
            safe_idx = jnp.maximum(qidx, 0)
            s_q = score[safe_idx]                       # [Q, P]
            l_q = label[safe_idx]
            gains_q = label_gain[l_q.astype(jnp.int32)]
            lam, hes = core(s_q, l_q, qvalid, inv_max_dcgs, gains_q,
                            discounts)
            # padded [Q, P] -> flat rows with one gather (each row occupies
            # exactly one padded position)
            g = lam[inv_pos]
            h = hes[inv_pos]
            if weight is not None:
                g = g * weight
                h = h * weight
            return g.astype(jnp.float32), h.astype(jnp.float32)
        return fn

    def payload_pos_fn(self):
        """Payload-order gradient mode for the persist fast path: scores
        arrive in PAYLOAD order with their global row ids; the padded
        [Q, P] slots are filled with ONE scatter through the static
        row->slot map and the lambdas return with one gather — no
        row-order round trip (the reference has no analog: its gradient
        buffer is always row-ordered, rank_objective.hpp:98-137)."""
        core = self._pairwise_flat()
        n = self.num_data

        def fn(score, rid, live, lab_pad, qvalid, inv_max_dcgs, gains_pad,
               discounts, pos_of_rid, w_pad):
            Q, P = lab_pad.shape
            QP = Q * P
            NP = score.shape[0]
            bc32 = functools.partial(jax.lax.bitcast_convert_type,
                                     new_dtype=jnp.float32)
            rid_c = jnp.minimum(rid, n - 1)
            # pos_of_rid is None when the row->slot map is the identity
            # (all queries the same length, no padding): skip the gather
            pos = rid_c if pos_of_rid is None else pos_of_rid[rid_c]
            pos = jnp.where(live, pos, QP)
            # ONE scatter plants both the padded scores and the inverse
            # slot->lane map (lane ids bitcast to ride the f32 scatter);
            # dead slots keep lane NP so the return scatter drops them
            lane = jnp.arange(NP, dtype=jnp.int32)
            init = jnp.stack([
                jnp.zeros((QP,), jnp.float32),
                jnp.broadcast_to(bc32(jnp.asarray(NP, jnp.int32)), (QP,))])
            spl = init.at[:, pos].set(
                jnp.stack([score, bc32(lane)]), mode="drop",
                unique_indices=True)
            sp = spl[0]
            inv = jax.lax.bitcast_convert_type(spl[1], jnp.int32)
            lam, hes = core(sp.reshape(Q, P), lab_pad, qvalid, inv_max_dcgs,
                            gains_pad, discounts)
            lam = lam[:QP]
            hes = hes[:QP]
            if w_pad is not None:
                # weights multiply BEFORE the f32 cast, exactly as the
                # row-order grad_fn does (rank_objective.hpp:165-170) —
                # pos-mode fns own their weighting; the grower's payload
                # weight row is not applied in pos mode
                lam = lam * w_pad.reshape(-1)
                hes = hes * w_pad.reshape(-1)
            # return via ONE scatter through the inverse map, not gathers:
            # on TPU an [NP]-sized gather serializes while the scatter of
            # a [2, n] block costs about the same as a [n] one
            out = jnp.zeros((2, NP), jnp.float32).at[:, inv].set(
                jnp.stack([lam.astype(jnp.float32),
                           hes.astype(jnp.float32)]),
                mode="drop", unique_indices=True)
            return out[0], out[1]
        return fn

    def _pos_grad_args(self):
        # device constants cached: persist_grad_args runs once per fused
        # K-iteration batch, and these [Q, P]/[n] planes never change
        cached = getattr(self, "_pos_args_dev", None)
        if cached is None:
            P = self._qidx.shape[1]
            from ..metrics.dcg import _DISCOUNT_CACHE
            # equal-length queries make the row->slot map the identity;
            # pass None and the pos fn skips that [n]-sized gather
            identity = bool(np.array_equal(
                self._inv_pos, np.arange(self.num_data, dtype=np.int32)))
            cached = self._pos_args_dev = (
                jnp.asarray(self._lab_pad), jnp.asarray(self._qvalid),
                jnp.asarray(self.inverse_max_dcgs),
                jnp.asarray(self._gains_pad),
                jnp.asarray(_DISCOUNT_CACHE[:P]),
                (None if identity else jnp.asarray(self._inv_pos)),
                (jnp.asarray(self._w_pad) if self._w_pad is not None
                 else None))
        return cached

    def _grad_args(self):
        weight = jnp.asarray(self.weight) if self.weight is not None else None
        P = self._qidx.shape[1]
        from ..metrics.dcg import _DISCOUNT_CACHE
        return (jnp.asarray(self.label), weight, jnp.asarray(self._qidx),
                jnp.asarray(self._qvalid), jnp.asarray(self.inverse_max_dcgs),
                jnp.asarray(self.label_gain),
                jnp.asarray(_DISCOUNT_CACHE[:P]),
                jnp.asarray(self._inv_pos))

    def to_string(self):
        return self.name


@register
class RankXENDCG(RankingObjective):
    name = "rank_xendcg"

    def device_gradients(self):
        # per-iteration fresh randomization cannot ride the fused
        # K-iteration scan (its traced inputs are fixed across the
        # batch): host-only, on the ONE capability surface
        return None

    # the reference's LCG (include/LightGBM/utils/random.h:101-110):
    # x = 214013 x + 2531011 (mod 2^32); NextFloat = ((x>>16) & 0x7fff)/2^15
    _LCG_A = np.uint32(214013)
    _LCG_B = np.uint32(2531011)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        qb = self.query_boundaries
        # flat row -> query id for segment ops
        qid = np.zeros(self.num_data, dtype=np.int32)
        for q in range(self.num_queries):
            qid[qb[q]:qb[q + 1]] = q
        self._qid = qid
        self._counts = np.diff(qb).astype(np.int32)
        # reference-exact per-query Random streams (rands_[i] = Random(seed+i),
        # rank_objective.hpp:300): vectorized k-step LCG jump tables so draw
        # j of a query reads the state after j+1 advances
        self._lcg_x = (np.uint32(self.seed)
                       + np.arange(self.num_queries, dtype=np.uint32))
        kmax = int(self._counts.max()) if len(self._counts) else 1
        A = np.empty(kmax + 1, dtype=np.uint32)
        C = np.empty(kmax + 1, dtype=np.uint32)
        A[0], C[0] = np.uint32(1), np.uint32(0)
        with np.errstate(over="ignore"):
            for k in range(kmax):
                A[k + 1] = self._LCG_A * A[k]
                C[k + 1] = self._LCG_A * C[k] + self._LCG_B
        self._lcg_A, self._lcg_C = A, C
        self._pos_in_query = (np.arange(self.num_data, dtype=np.int64)
                              - qb[qid]).astype(np.int64)

    def _next_floats(self) -> np.ndarray:
        """One iteration's [num_data] NextFloat() draws, bit-identical to
        the reference's sequential per-query stream."""
        j1 = self._pos_in_query + 1
        with np.errstate(over="ignore"):
            v = (self._lcg_A[j1] * self._lcg_x[self._qid]
                 + self._lcg_C[j1])
            cnt = self._counts.astype(np.int64)
            self._lcg_x = (self._lcg_A[cnt] * self._lcg_x
                           + self._lcg_C[cnt])
        return (((v >> np.uint32(16)) & np.uint32(0x7FFF))
                .astype(np.float32) / np.float32(32768.0)).astype(np.float64)

    def grad_fn(self):
        num_queries = self.num_queries
        num_data = self.num_data

        def seg_sum(x, qid):
            return jax.ops.segment_sum(x, qid, num_segments=num_queries)

        def seg_max(x, qid):
            return jax.ops.segment_max(x, qid, num_segments=num_queries)

        def fn(score, label, weight, qid, counts, g_rand):
            # masked softmax per query (Common::Softmax over each query)
            mx = seg_max(score, qid)
            e = jnp.exp(score - mx[qid])
            rho = e / seg_sum(e, qid)[qid]

            phi = jnp.power(2.0, jnp.floor(label).astype(jnp.float64)) - g_rand
            sum_labels = jnp.maximum(K_EPSILON, seg_sum(phi, qid))
            l1 = -phi / sum_labels[qid] + rho
            sum_l1 = seg_sum(l1, qid)
            l2 = (sum_l1[qid] - l1) / (1.0 - rho)
            sum_l2 = seg_sum(l2, qid)
            l3 = (sum_l2[qid] - l2) / (1.0 - rho)
            lambdas_multi = l1 + rho * l2 + rho * rho * l3
            # single-document queries: l2/l3 terms are zero (cnt<=1 branch)
            single = (counts[qid] <= 1)
            lambdas = jnp.where(single, l1, lambdas_multi)
            hess = rho * (1.0 - rho)
            if weight is not None:
                lambdas = lambdas * weight
                hess = hess * weight
            return lambdas.astype(jnp.float32), hess.astype(jnp.float32)
        return fn

    def get_gradients(self, score):
        # fresh randomization each iteration (reference draws from per-query
        # Random streams each GetGradients call, rank_objective.hpp:305-312)
        if getattr(self, "_jit_fn", None) is None:
            self._jit_fn = jax.jit(self.grad_fn())
            weight = jnp.asarray(self.weight) if self.weight is not None else None
            self._jit_args = (jnp.asarray(self.label), weight,
                              jnp.asarray(self._qid), jnp.asarray(self._counts))
        return self._jit_fn(score, *self._jit_args,
                            jnp.asarray(self._next_floats()))

    def to_string(self):
        return self.name
