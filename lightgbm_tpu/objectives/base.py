"""Objective function interface + factory.

TPU-native rebuild of the reference objective layer
(include/LightGBM/objective_function.h, factory
src/objective/objective_function.cpp:15-53). Per-row (grad, hess) math runs
as one jitted vectorized function over the whole score vector — the TPU
equivalent of the reference's OpenMP loops — while the scalar decisions
(BoostFromScore, leaf renewal percentiles) stay host-side numpy, mirroring
where the reference computes them (on scalars / per-leaf subsets).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ..utils.log import Log

# reference include/LightGBM/meta.h:51
K_EPSILON = 1e-15


class ObjectiveFunction:
    """Base objective (objective_function.h).

    Subclasses set `name` and implement `grad_fn()` returning a pure
    function (score, label, weight) -> (grad, hess) traced by jit once.
    `score` is [num_data] for single-model objectives and
    [num_class, num_data] for multiclass (reference layout: class-major,
    gbdt.cpp grad buffer is num_data * num_tree_per_iteration).
    """

    name = "none"

    def __init__(self, config):
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None

    # -- lifecycle ------------------------------------------------------
    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight

    # -- behavior flags (objective_function.h) --------------------------
    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def num_predict_one_row(self) -> int:
        return 1

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def device_gradients(self):
        """THE capability surface for the fused boosting scan: the
        device-side gradient kernel as (mode, fn), or None when this
        objective is host-only. mode selects the scan driver's fill
        contract — 'payload' (label-only, fastest; also the
        K-tree-per-iteration snapshot fill), 'pos' (payload-order with
        row-id scatter, lambdarank), 'row' (full row-order round trip
        through the objective's standard grad_fn). Objectives whose
        gradients need fresh per-iteration HOST inputs (rank_xendcg's
        randomization) override this to return None — the traced
        inputs of the compiled K-iteration program are fixed for the
        whole batch. `supports_fused_scan` derives from this; the two
        flags are one surface."""
        if getattr(self, "num_model_per_iteration", 1) > 1:
            fn = self.payload_grad_fn_multi()
            return ("payload", fn) if fn is not None else None
        fn = self.payload_grad_fn()
        if fn is not None:
            return ("payload", fn)
        fn = self.payload_pos_fn()
        if fn is not None:
            return ("pos", fn)
        return ("row", self.grad_fn())

    @property
    def supports_fused_scan(self) -> bool:
        """Derived view of device_gradients() — kept for the booster's
        batch gate; never override this, override device_gradients."""
        return self.device_gradients() is not None

    @property
    def average_output(self) -> bool:
        """RF sets this through boosting, not the objective (kept for parity
        with ObjectiveFunction::IsAverageOutput used by ScoreUpdater)."""
        return False

    def class_need_train(self, class_id: int) -> bool:
        return True

    # -- main hooks -----------------------------------------------------
    def grad_fn(self) -> Callable:
        """Return pure (score, *device_args) -> (grad, hess); jax code.
        device_args defaults to (label, weight) — see `_grad_args`."""
        raise NotImplementedError

    def payload_grad_fn(self):
        """Pure (score, label) -> (grad, hess) for the persistent-payload
        scan (ops/grow_persist.py), where the LABEL rides in the payload and
        no other per-row inputs exist. Returns None when this objective
        needs more than the label (weights, query groups, per-iteration
        host inputs) — those configurations take the v1 path."""
        return None

    def payload_grad_fn_multi(self):
        """K-tree-per-iteration analog of payload_grad_fn: pure
        (scores [K, NP], label, cls) -> (grad, hess) for class `cls`,
        where `scores` is the payload's per-class score block (snapshot
        at iteration start). None when unsupported."""
        return None

    def payload_pos_fn(self):
        """Pure (score, rid, live, *pos_args) -> (grad, hess) ALL in
        payload order, for objectives whose gradients need global row
        structure (lambdarank's query groups) but can reach it through the
        carried row-id payload row with one scatter instead of a full
        row-order round trip. None when unsupported (the persist driver
        then falls back to row-order mode)."""
        return None

    def persist_grad_mode(self) -> str:
        """Which gradient mode the persist scan driver should use —
        a view of device_gradients(); 'row' for host-only objectives
        (they never reach the driver, can_persist_scan gates them)."""
        dg = self.device_gradients()
        return dg[0] if dg is not None else "row"

    def persist_grad_args(self) -> tuple:
        """Extra traced args for the persist driver's gradient fill,
        matching persist_grad_mode ('payload' mode takes none)."""
        mode = self.persist_grad_mode()
        if mode == "payload":
            return ()
        if mode == "pos":
            return self._pos_grad_args()
        return self._grad_args()

    def _grad_args(self):
        """Device arrays bound as extra args of the jitted grad function."""
        import jax.numpy as jnp
        label = jnp.asarray(self.label) if self.label is not None else None
        weight = jnp.asarray(self.weight) if self.weight is not None else None
        return (label, weight)

    def get_gradients(self, score):
        """score (device array) -> (grad, hess) on device, jit-compiled."""
        if getattr(self, "_jit_fn", None) is None:
            import jax
            self._jit_fn = jax.jit(self.grad_fn())
            self._jit_args = self._grad_args()
        return self._jit_fn(score, *self._jit_args)

    def boost_from_score(self, class_id: int) -> float:
        """Initial score (BoostFromScore); host-side."""
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Raw scores -> user-facing predictions (ConvertOutput)."""
        return raw

    def renew_tree_output(self, pred_in_leaf: np.ndarray,
                          label_in_leaf: np.ndarray,
                          weight_in_leaf: Optional[np.ndarray]) -> float:
        """New leaf output from the leaf's rows (RenewTreeOutput)."""
        raise NotImplementedError

    def static_fingerprint(self) -> tuple:
        """Hashable digest of every scalar the grad_fn CLOSURE bakes in
        (sigmoid, class weights, alpha, need_train flags, ...). Compiled-
        program caches keyed on this stay valid across objective instances
        with equal hyperparameters while instances that differ in any
        scalar get their own compilation. Device arrays (label, weight,
        masks) are excluded — they are traced arguments, not constants."""
        items = []
        for k, v in sorted(vars(self).items()):
            if k == "config":
                continue
            if isinstance(v, (np.number, np.bool_)):
                items.append((k, v.item()))
            elif isinstance(v, (int, float, bool, str, bytes, type(None))):
                items.append((k, v))
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, float, bool, str, np.number))
                    for x in v):
                items.append((k, tuple(
                    x.item() if isinstance(x, np.number) else x for x in v)))
        return (type(self).__name__, tuple(items))

    def to_string(self) -> str:
        """Model-file objective string (ToString)."""
        return self.name

    def __str__(self) -> str:
        return self.to_string()


# ---------------------------------------------------------------------------
# percentile helpers — exact reference semantics
# (PercentileFun / WeightedPercentileFun, src/objective/regression_objective.hpp:18-90)
# ---------------------------------------------------------------------------

def percentile(data: np.ndarray, alpha: float) -> float:
    """Reference PercentileFun: interpolated percentile computed from the top."""
    data = np.asarray(data, dtype=np.float64)
    n = len(data)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(data[0])
    s = np.sort(data)[::-1]  # descending
    float_pos = (1.0 - alpha) * n
    pos = int(float_pos)
    if pos < 1:
        return float(s[0])
    if pos >= n:
        return float(s[-1])
    bias = float_pos - pos
    v1 = float(s[pos - 1])
    v2 = float(s[pos])
    return v1 - (v1 - v2) * bias


def weighted_percentile(data: np.ndarray, weight: np.ndarray,
                        alpha: float) -> float:
    """Reference WeightedPercentileFun (stable sort + weighted cdf walk)."""
    data = np.asarray(data, dtype=np.float64)
    weight = np.asarray(weight, dtype=np.float64)
    n = len(data)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(data[0])
    order = np.argsort(data, kind="stable")
    cdf = np.cumsum(weight[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(data[order[pos]])
    v1 = float(data[order[pos - 1]])
    v2 = float(data[order[pos]])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return float((threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos])
                     * (v2 - v1) + v1)
    return v2


# ---------------------------------------------------------------------------
# factory (objective_function.cpp:15-53)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


def create_objective(name: str, config) -> Optional[ObjectiveFunction]:
    """ObjectiveFunction::CreateObjectiveFunction. Returns None for 'none'
    (custom objective driven from the binding layer, like the reference)."""
    # late imports populate the registry
    from . import binary, multiclass, rank, regression, xentropy  # noqa: F401
    if name in ("none", "null", "custom", "na", ""):
        return None
    if name not in _REGISTRY:
        Log.fatal("Unknown objective type name: %s" % name)
    return _REGISTRY[name](config)


def parse_objective_string(s: str, config) -> Optional[ObjectiveFunction]:
    """Rebuild an objective from a model-file string like
    'binary sigmoid:1' (reference CreateObjectiveFunction(str) overload)."""
    parts = s.strip().split()
    if not parts:
        return None
    name = parts[0]
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            if k == "sigmoid":
                config.sigmoid = float(v)
            elif k == "num_class":
                config.num_class = int(v)
        elif tok == "sqrt":
            config.reg_sqrt = True
    return create_objective(name, config)
