"""Multiclass objectives (softmax and one-vs-all).

TPU-native rebuild of src/objective/multiclass_objective.hpp: K trees per
iteration (NumModelPerIteration :144,:249), class-major [K, N] score layout
matching the reference's num_data*k + i indexing (:91), softmax grad/hess
(:84-126) vectorized over the class axis instead of a per-row rec buffer.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..utils.log import Log
from .base import K_EPSILON, ObjectiveFunction, register
from .binary import BinaryLogloss


@register
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = self.label.astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            Log.fatal("Label must be in [0, %d), but found %d in label"
                      % (self.num_class, int(label_int.min() if label_int.min() < 0
                                             else label_int.max())))
        self.label_int = label_int
        if self.weight is None:
            probs = np.bincount(label_int, minlength=self.num_class).astype(np.float64)
            sum_weight = float(num_data)
        else:
            probs = np.zeros(self.num_class)
            np.add.at(probs, label_int, self.weight.astype(np.float64))
            sum_weight = float(np.sum(self.weight))
        self.class_init_probs = probs / sum_weight

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def grad_fn(self):
        import jax

        num_class = self.num_class

        def fn(score, label_int, weight):
            # score: [K, N] class-major
            p = jax.nn.softmax(score, axis=0)
            onehot = jax.nn.one_hot(label_int, num_class, axis=0,
                                    dtype=score.dtype)
            g = p - onehot
            h = 2.0 * p * (1.0 - p)
            if weight is None:
                return g, h
            return g * weight[None, :], h * weight[None, :]
        return fn

    def _grad_args(self):
        weight = jnp.asarray(self.weight) if self.weight is not None else None
        return (jnp.asarray(self.label_int), weight)

    def payload_grad_fn_multi(self):
        """Per-class softmax grads from the payload score block
        (multiclass_objective.hpp:84-126). The label row carries the raw
        class index as f32. The softmax normalization is recomputed per
        class (O(K^2 N) per iteration instead of O(K N)): the payload
        permutes between class trees, so a shared denominator would need
        its own payload row — not worth one until profiles say the exp/sum
        shows up next to the split kernels. Weights ride the payload
        and multiply AFTER this fn (grow_persist._apply_weight)."""

        def fn(scores, label, cls):
            m = jnp.max(scores, axis=0)
            e = jnp.exp(scores - m)
            p = e[cls] / jnp.sum(e, axis=0)
            onehot = (label.astype(jnp.int32) == cls).astype(p.dtype)
            return p - onehot, 2.0 * p * (1.0 - p)
        return fn

    def boost_from_score(self, class_id):
        return float(np.log(max(K_EPSILON, self.class_init_probs[class_id])))

    def class_need_train(self, class_id):
        p = self.class_init_probs[class_id]
        return not (abs(p) <= K_EPSILON or abs(p) >= 1.0 - K_EPSILON)

    def convert_output(self, raw):
        """raw: [..., K] row-major per-row scores -> softmax probabilities."""
        m = np.max(raw, axis=-1, keepdims=True)
        e = np.exp(raw - m)
        return e / np.sum(e, axis=-1, keepdims=True)

    def to_string(self):
        return "%s num_class:%d" % (self.name, self.num_class)


@register
class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self.binary_losses = []
        for k in range(self.num_class):
            self.binary_losses.append(
                BinaryLogloss(config,
                              is_pos=(lambda y, kk=k: y.astype(np.int32) == kk)))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for b in self.binary_losses:
            b.init(metadata, num_data)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def get_gradients(self, score):
        # score: [K, N]; per-class binary grads stacked
        gs, hs = [], []
        for k, b in enumerate(self.binary_losses):
            g, h = b.get_gradients(score[k])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs), jnp.stack(hs)

    def payload_grad_fn_multi(self):
        """Per-class one-vs-all binary grads (multiclass_objective.hpp:180+);
        class k's positives are payload-label == k; weights multiply
        after (grow_persist._apply_weight)."""
        if not all(b.need_train for b in self.binary_losses):
            return None
        fns = [b.grad_fn() for b in self.binary_losses]

        def fn(scores, label, cls):
            return fns[cls](scores[cls], label.astype(jnp.int32) == cls,
                            None)
        return fn

    def boost_from_score(self, class_id):
        return self.binary_losses[class_id].boost_from_score(0)

    def class_need_train(self, class_id):
        return self.binary_losses[class_id].class_need_train(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return "%s num_class:%d sigmoid:%g" % (self.name, self.num_class,
                                               self.sigmoid)
