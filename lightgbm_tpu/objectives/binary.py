"""Binary log-loss objective.

TPU-native rebuild of src/objective/binary_objective.hpp:21-221: label-
conditional ±1 encoding and per-class weights (is_unbalance /
scale_pos_weight, :95-105), sigmoid-scaled logistic grad/hess (:109-140)
as one vectorized jax function, BoostFromScore prior log-odds (:143-165).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..utils.log import Log
from .base import K_EPSILON, ObjectiveFunction, register


@register
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero"
                      % self.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            Log.fatal("Cannot set is_unbalance and scale_pos_weight "
                      "at the same time")
        self.is_pos = is_pos if is_pos is not None else (lambda y: y > 0)
        self.need_train = True
        self.num_pos_data = 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos_mask = self.is_pos(self.label)
        cnt_positive = int(np.count_nonzero(pos_mask))
        cnt_negative = num_data - cnt_positive
        self.num_pos_data = cnt_positive
        self.need_train = not (cnt_positive == 0 or cnt_negative == 0)
        if not self.need_train:
            Log.warning("Contains only one class")
        Log.info("Number of positive: %d, number of negative: %d"
                 % (cnt_positive, cnt_negative))
        label_weights = [1.0, 1.0]   # [negative, positive]
        if self.is_unbalance and cnt_positive > 0 and cnt_negative > 0:
            if cnt_positive > cnt_negative:
                label_weights[0] = cnt_positive / cnt_negative
            else:
                label_weights[1] = cnt_negative / cnt_positive
        label_weights[1] *= self.scale_pos_weight
        self.label_weights = label_weights
        self._pos_mask = pos_mask

    def grad_fn(self):
        sig = self.sigmoid
        w_neg, w_pos = self.label_weights
        need_train = self.need_train

        def fn(score, pos_mask, weight):
            if not need_train:
                z = jnp.zeros_like(score)
                return z, z
            # dtype-following ±1 and weights: python-float select
            # branches materialize a weak f64 under x64 (narrowed back
            # at the next multiply — same bits, since ±1 is exact and
            # the weights round identically either way — but the
            # persist-f32 audit rightly refuses f64 intermediates in
            # the device gradient kernel)
            y = jnp.where(pos_mask, jnp.asarray(1.0, score.dtype),
                          jnp.asarray(-1.0, score.dtype))
            lw = jnp.where(pos_mask, jnp.asarray(w_pos, score.dtype),
                           jnp.asarray(w_neg, score.dtype))
            response = -y * sig / (1.0 + jnp.exp(y * sig * score))
            abs_resp = jnp.abs(response)
            g = response * lw
            h = abs_resp * (sig - abs_resp) * lw
            if weight is None:
                return g, h
            return g * weight, h * weight
        return fn

    def _grad_args(self):
        weight = jnp.asarray(self.weight) if self.weight is not None else None
        return (jnp.asarray(self._pos_mask), weight)

    def payload_grad_fn(self):
        # weights ride the payload and multiply AFTER this fn
        if not self.need_train:
            return None
        base = self.grad_fn()

        def fn(score, label):
            return base(score, label > 0, None)
        return fn

    def boost_from_score(self, class_id):
        pos = self._pos_mask.astype(np.float64)
        if self.weight is not None:
            pavg = float(np.sum(pos * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(pos))
        pavg = min(pavg, 1.0 - K_EPSILON)
        pavg = max(pavg, K_EPSILON)
        initscore = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        Log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f"
                 % (self.name, pavg, initscore))
        return initscore

    def class_need_train(self, class_id):
        return self.need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return "%s sigmoid:%g" % (self.name, self.sigmoid)
