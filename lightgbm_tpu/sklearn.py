"""scikit-learn estimator wrappers.

TPU-native rebuild of python-package/lightgbm/sklearn.py: LGBMModel (:169)
with LGBMRegressor (:744), LGBMClassifier (:771), LGBMRanker (:913); custom
objective/eval adapters (:21-160) translating sklearn-style fobj(y_true,
y_pred) into the engine's fobj(preds, dataset) convention.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from .basic import Booster, Dataset, _data_to_2d
from .engine import train
from .utils.log import LightGBMError

try:
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    from sklearn.preprocessing import LabelEncoder as _LabelEncoder
    _SKLEARN = True
except ImportError:  # pragma: no cover
    _SKBase = object

    class _SKClassifier:  # noqa: N801
        pass

    class _SKRegressor:  # noqa: N801
        pass
    _LabelEncoder = None
    _SKLEARN = False


class _ObjectiveFunctionWrapper:
    """sklearn fobj(y_true, y_pred[, weight|group]) -> engine fobj
    (reference sklearn.py:21-97)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError("Self-defined objective function should have "
                            "2, 3 or 4 arguments, got %d" % argc)
        return grad, hess


class _EvalFunctionWrapper:
    """sklearn feval(y_true, y_pred[, weight|group]) -> engine feval
    (reference sklearn.py:100-160)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError("Self-defined eval function should have 2, 3 or 4 "
                        "arguments, got %d" % argc)


class LGBMModel(_SKBase):
    """Base sklearn estimator (reference sklearn.py:169)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0., min_child_weight=1e-3,
                 min_child_samples=20, subsample=1., subsample_freq=0,
                 colsample_bytree=1., reg_alpha=0., reg_lambda=0.,
                 random_state=None, n_jobs=-1, silent=True,
                 importance_type="split", **kwargs):
        if not _SKLEARN:
            raise LightGBMError("scikit-learn is required for lightgbm_tpu."
                                "sklearn")
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self.class_weight = class_weight
        self._Booster: Optional[Booster] = None
        self._evals_result = None
        self._best_score = None
        self._best_iteration = None
        self._other_params: Dict[str, Any] = {}
        self._objective = objective
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self.set_params(**kwargs)

    # -- sklearn plumbing ----------------------------------------------
    def get_params(self, deep=True):
        params = super().get_params(deep=deep) if _SKLEARN else {}
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, "_other_params") and \
                    key not in self.__init__.__code__.co_varnames:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        alias = {"boosting_type": "boosting",
                 "min_split_gain": "min_gain_to_split",
                 "min_child_weight": "min_sum_hessian_in_leaf",
                 "min_child_samples": "min_data_in_leaf",
                 "subsample": "bagging_fraction",
                 "subsample_freq": "bagging_freq",
                 "colsample_bytree": "feature_fraction",
                 "subsample_for_bin": "bin_construct_sample_cnt",
                 "reg_alpha": "lambda_l1",
                 "reg_lambda": "lambda_l2",
                 "random_state": "seed",
                 "n_jobs": "num_threads"}
        out = {}
        for k, v in params.items():
            k = alias.get(k, k)
            if v is None and k in ("objective", "seed"):
                continue
            out[k] = v
        if callable(self._objective):
            out.pop("objective", None)
        out.setdefault("objective", self._default_objective())
        out["verbosity"] = -1 if self.silent else 1
        if out.get("num_threads") in (-1, None):
            out.pop("num_threads", None)
        return out

    # -- training -------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None):
        params = self._process_params()
        fobj = None
        if callable(self._objective):
            fobj = _ObjectiveFunctionWrapper(self._objective)
            params["objective"] = "none"
        feval = None
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
        elif isinstance(eval_metric, str):
            params["metric"] = eval_metric
        elif isinstance(eval_metric, (list, tuple)):
            params["metric"] = list(eval_metric)

        y = np.asarray(y).reshape(-1)
        if self.class_weight is not None:
            cw = self._compute_class_weights(y)
            # class weights multiply into any user-provided sample weights
            # (reference sklearn.py fit: _LGBMComputeSampleWeight product)
            sample_weight = cw if sample_weight is None else \
                np.asarray(sample_weight, dtype=np.float64) * cw
        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, init_score=init_score,
                            params=params)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vy = np.asarray(vy).reshape(-1)
                if self._classes is not None:
                    vy = self._le.transform(vy)
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                if eval_class_weight is not None and \
                        i < len(eval_class_weight) and \
                        eval_class_weight[i] is not None:
                    # computed on encoded labels — same key space as the
                    # training class_weight (y reaches this method encoded)
                    from sklearn.utils.class_weight import \
                        compute_sample_weight
                    vcw = compute_sample_weight(eval_class_weight[i], vy)
                    vw = vcw if vw is None else \
                        np.asarray(vw, dtype=np.float64) * vcw
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                valid_sets.append(Dataset(vx, label=vy, weight=vw, group=vg,
                                          init_score=vi, reference=train_set,
                                          params=params))
        evals_result: Dict = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        X2, _, _ = _data_to_2d(X)
        self._n_features = X2.shape[1]
        self.fitted_ = True
        return self

    def _compute_class_weights(self, y):
        from sklearn.utils.class_weight import compute_sample_weight
        return compute_sample_weight(self.class_weight, y)

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, predict_device=None,
                **kwargs):
        """predict_device="tpu" serves through the compiled device runtime
        (predict/ subsystem); None defers to the fit params / the "cpu"
        numpy-walk default."""
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before "
                                "exploiting the model.")
        if predict_device is not None:
            kwargs = dict(kwargs, predict_device=predict_device)
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib, **kwargs)

    # -- accessors ------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit first.")
        return self._Booster

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def best_score_(self):
        return self._best_score

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def n_features_(self):
        return self._n_features

    @property
    def feature_importances_(self):
        return self.booster_.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self):
        return self.booster_.feature_name()

    @property
    def objective_(self):
        return self._objective or self._default_objective()


class LGBMRegressor(LGBMModel, _SKRegressor):
    """LightGBM regressor (reference sklearn.py:744)."""

    def _default_objective(self):
        return "regression"


class LGBMClassifier(LGBMModel, _SKClassifier):
    """LightGBM classifier (reference sklearn.py:771)."""

    def _default_objective(self):
        return "binary" if (self._n_classes or 2) <= 2 else "multiclass"

    def fit(self, X, y, sample_weight=None, init_score=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_metric=None,
            early_stopping_rounds=None, verbose=True, feature_name="auto",
            categorical_feature="auto", callbacks=None):
        y = np.asarray(y).reshape(-1)
        self._le = _LabelEncoder().fit(y)
        y_enc = self._le.transform(y)
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        params_extra = {}
        if self._n_classes > 2:
            params_extra["num_class"] = self._n_classes
        for k, v in params_extra.items():
            self._other_params[k] = v
            setattr(self, k, v)
        super().fit(X, y_enc, sample_weight=sample_weight,
                    init_score=init_score, eval_set=eval_set,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_class_weight=eval_class_weight,
                    eval_init_score=eval_init_score, eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks)
        return self

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration, pred_leaf,
                                    pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            idx = (result > 0.5).astype(np.int32)
        else:
            idx = np.argmax(result, axis=1)
        return self._le.inverse_transform(idx)

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = super().predict(X, raw_score, num_iteration, pred_leaf,
                                 pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.column_stack([1.0 - result, result])
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (reference sklearn.py:913)."""

    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), early_stopping_rounds=None,
            verbose=True, feature_name="auto", categorical_feature="auto",
            callbacks=None):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is "
                             "not None")
        self._other_params["eval_at"] = list(eval_at)
        self.eval_at = list(eval_at)
        super().fit(X, y, sample_weight=sample_weight,
                    init_score=init_score, group=group, eval_set=eval_set,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score, eval_group=eval_group,
                    eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks)
        return self
