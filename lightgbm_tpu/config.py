"""Parameter/config system.

TPU-native rebuild of the reference config layer (include/LightGBM/config.h:32,
src/io/config.cpp:186, src/io/config_auto.cpp). The reference generates its parser
and docs from an annotated struct; here a single PARAMS schema table is the source
of truth for names, types, defaults, aliases and range checks. `Config` resolves
aliases (ParameterAlias::KeyAliasTransform, config.h:979), applies precedence
(explicit key wins over alias), parses CLI "key=value" strings (Config::KV2Map,
config.h:79) and exposes typed attributes.

New TPU-specific parameters are added under the same scheme (device_type=tpu,
tpu_* tuning knobs) — the analog of the reference's gpu_* block (config.h:894-902).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .utils.log import Log


class _P:
    """One parameter spec: name, type tag, default, aliases, (min, max) check."""

    __slots__ = ("name", "type", "default", "aliases", "lo", "hi", "lo_excl")

    def __init__(self, name, type_, default, aliases=(), lo=None, hi=None, lo_excl=False):
        self.name = name
        self.type = type_
        self.default = default
        self.aliases = tuple(aliases)
        self.lo = lo
        self.hi = hi
        self.lo_excl = lo_excl


# Schema: every supported parameter. Mirrors the reference's parameter inventory
# (config.h structured comments; alias table in config_auto.cpp).
PARAMS: List[_P] = [
    # ---- Core ----
    _P("config", str, "", ("config_file",)),
    _P("task", str, "train", ("task_type",)),
    _P("objective", str, "regression",
       ("objective_type", "app", "application")),
    _P("boosting", str, "gbdt", ("boosting_type", "boost")),
    _P("data", str, "", ("train", "train_data", "train_data_file", "data_filename")),
    _P("valid", "vstr", [], ("test", "valid_data", "valid_data_file", "test_data",
                             "test_data_file", "valid_filenames")),
    _P("num_iterations", int, 100,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round", "num_rounds",
        "num_boost_round", "n_estimators"), lo=0),
    _P("learning_rate", float, 0.1, ("shrinkage_rate", "eta"), lo=0.0, lo_excl=True),
    _P("num_leaves", int, 31, ("num_leaf", "max_leaves", "max_leaf"), lo=2, hi=131072),
    _P("tree_learner", str, "serial", ("tree", "tree_type", "tree_learner_type")),
    _P("num_threads", int, 0, ("num_thread", "nthread", "nthreads", "n_jobs")),
    _P("device_type", str, "tpu", ("device",)),
    _P("seed", "opt_int", None, ("random_seed", "random_state")),
    # ---- Learning control ----
    _P("max_depth", int, -1),
    _P("min_data_in_leaf", int, 20,
       ("min_data_per_leaf", "min_data", "min_child_samples"), lo=0),
    _P("min_sum_hessian_in_leaf", float, 1e-3,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight"),
       lo=0.0),
    _P("bagging_fraction", float, 1.0, ("sub_row", "subsample", "bagging"),
       lo=0.0, hi=1.0, lo_excl=True),
    _P("pos_bagging_fraction", float, 1.0,
       ("pos_sub_row", "pos_subsample", "pos_bagging"), lo=0.0, hi=1.0, lo_excl=True),
    _P("neg_bagging_fraction", float, 1.0,
       ("neg_sub_row", "neg_subsample", "neg_bagging"), lo=0.0, hi=1.0, lo_excl=True),
    _P("bagging_freq", int, 0, ("subsample_freq",)),
    _P("bagging_seed", int, 3, ("bagging_fraction_seed",)),
    _P("feature_fraction", float, 1.0, ("sub_feature", "colsample_bytree"),
       lo=0.0, hi=1.0, lo_excl=True),
    _P("feature_fraction_bynode", float, 1.0,
       ("sub_feature_bynode", "colsample_bynode"), lo=0.0, hi=1.0, lo_excl=True),
    _P("feature_fraction_seed", int, 2),
    _P("early_stopping_round", int, 0,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    _P("first_metric_only", bool, False),
    _P("max_delta_step", float, 0.0, ("max_tree_output", "max_leaf_output")),
    _P("lambda_l1", float, 0.0, ("reg_alpha",), lo=0.0),
    _P("lambda_l2", float, 0.0, ("reg_lambda", "lambda"), lo=0.0),
    _P("min_gain_to_split", float, 0.0, ("min_split_gain",), lo=0.0),
    _P("drop_rate", float, 0.1, ("rate_drop",), lo=0.0, hi=1.0),
    _P("max_drop", int, 50),
    _P("skip_drop", float, 0.5, lo=0.0, hi=1.0),
    _P("xgboost_dart_mode", bool, False),
    _P("uniform_drop", bool, False),
    _P("drop_seed", int, 4),
    _P("top_rate", float, 0.2, lo=0.0, hi=1.0),
    _P("other_rate", float, 0.1, lo=0.0, hi=1.0),
    _P("min_data_per_group", int, 100, lo=1),
    _P("max_cat_threshold", int, 32, lo=1),
    _P("cat_l2", float, 10.0, lo=0.0),
    _P("cat_smooth", float, 10.0, lo=0.0),
    _P("max_cat_to_onehot", int, 4, lo=1),
    _P("top_k", int, 20, ("topk",), lo=1),
    _P("monotone_constraints", "vint", [], ("mc", "monotone_constraint")),
    _P("feature_contri", "vdouble", [],
       ("feature_contrib", "fc", "fp", "feature_penalty")),
    _P("forcedsplits_filename", str, "",
       ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits")),
    _P("forcedbins_filename", str, ""),
    _P("refit_decay_rate", float, 0.9, lo=0.0, hi=1.0),
    _P("cegb_tradeoff", float, 1.0, lo=0.0),
    _P("cegb_penalty_split", float, 0.0, lo=0.0),
    _P("cegb_penalty_feature_lazy", "vdouble", []),
    _P("cegb_penalty_feature_coupled", "vdouble", []),
    _P("extra_trees", bool, False, ("extra_tree",)),
    _P("extra_seed", int, 6),
    # ---- IO / dataset ----
    _P("verbosity", int, 1, ("verbose",)),
    _P("max_bin", int, 255, lo=1),
    _P("min_data_in_bin", int, 3, lo=1),
    _P("bin_construct_sample_cnt", int, 200000, ("subsample_for_bin",), lo=1),
    _P("histogram_pool_size", float, -1.0, ("hist_pool_size",)),
    _P("data_random_seed", int, 1, ("data_seed",)),
    _P("output_model", str, "LightGBM_model.txt", ("model_output", "model_out")),
    _P("snapshot_freq", int, -1, ("save_period",)),
    _P("input_model", str, "", ("model_input", "model_in")),
    _P("output_result", str, "LightGBM_predict_result.txt",
       ("predict_result", "prediction_result", "predict_name", "prediction_name",
        "pred_name", "name_pred")),
    _P("initscore_filename", str, "",
       ("init_score_filename", "init_score_file", "init_score", "input_init_score")),
    _P("valid_data_initscores", "vstr", [],
       ("valid_data_init_scores", "valid_init_score_file", "valid_init_score")),
    _P("pre_partition", bool, False, ("is_pre_partition",)),
    _P("enable_bundle", bool, True, ("is_enable_bundle", "bundle")),
    _P("max_conflict_rate", float, 0.0, lo=0.0, hi=1.0),
    _P("is_enable_sparse", bool, True, ("is_sparse", "enable_sparse", "sparse")),
    _P("sparse_threshold", float, 0.8, lo=0.0, hi=1.0, lo_excl=True),
    _P("use_missing", bool, True),
    _P("zero_as_missing", bool, False),
    _P("two_round", bool, False, ("two_round_loading", "use_two_round_loading")),
    _P("save_binary", bool, False, ("is_save_binary", "is_save_binary_file")),
    _P("header", bool, False, ("has_header",)),
    _P("label_column", str, "", ("label",)),
    _P("weight_column", str, "", ("weight",)),
    _P("group_column", str, "",
       ("group", "group_id", "query_column", "query", "query_id")),
    _P("ignore_column", str, "", ("ignore_feature", "blacklist")),
    _P("categorical_feature", str, "",
       ("cat_feature", "categorical_column", "cat_column")),
    _P("predict_raw_score", bool, False,
       ("is_predict_raw_score", "predict_rawscore", "raw_score")),
    _P("predict_leaf_index", bool, False, ("is_predict_leaf_index", "leaf_index")),
    _P("predict_contrib", bool, False, ("is_predict_contrib", "contrib")),
    _P("num_iteration_predict", int, -1),
    _P("pred_early_stop", bool, False),
    _P("pred_early_stop_freq", int, 10),
    _P("pred_early_stop_margin", float, 10.0),
    _P("convert_model_language", str, ""),
    _P("convert_model", str, "gbdt_prediction.cpp", ("convert_model_file",)),
    # ---- Objective ----
    _P("num_class", int, 1, ("num_classes",), lo=1),
    _P("is_unbalance", bool, False, ("unbalance", "unbalanced_sets")),
    _P("scale_pos_weight", float, 1.0, lo=0.0),
    _P("sigmoid", float, 1.0, lo=0.0, lo_excl=True),
    _P("boost_from_average", bool, True),
    _P("reg_sqrt", bool, False),
    _P("alpha", float, 0.9, lo=0.0, lo_excl=True),
    _P("fair_c", float, 1.0, lo=0.0, lo_excl=True),
    _P("poisson_max_delta_step", float, 0.7, lo=0.0, lo_excl=True),
    _P("tweedie_variance_power", float, 1.5, lo=1.0, hi=2.0),
    _P("max_position", int, 20, lo=1),
    _P("lambdarank_truncation_level", int, 20, lo=1),
    _P("lambdarank_norm", bool, True, ("lambdamart_norm",)),
    _P("label_gain", "vdouble", []),
    _P("objective_seed", int, 5),
    # ---- Metric ----
    _P("metric", "vstr", [], ("metrics", "metric_types")),
    _P("metric_freq", int, 1, ("output_freq",), lo=1),
    _P("is_provide_training_metric", bool, False,
       ("training_metric", "is_training_metric", "train_metric")),
    _P("eval_at", "vint", [1, 2, 3, 4, 5],
       ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
    _P("multi_error_top_k", int, 1, lo=1),
    _P("auc_mu_weights", "vdouble", []),
    # ---- Network ----
    _P("num_machines", int, 1, ("num_machine",), lo=1),
    _P("local_listen_port", int, 12400, ("local_port", "port"), lo=1),
    _P("time_out", int, 120, lo=1),
    _P("machine_list_filename", str, "",
       ("machine_list_file", "machine_list", "mlist")),
    _P("machines", str, "", ("workers", "nodes")),
    # ---- GPU (accepted for compatibility; ignored on TPU) ----
    _P("gpu_platform_id", int, -1),
    _P("gpu_device_id", int, -1),
    _P("gpu_use_dp", bool, False),
    # ---- TPU (new; analog of the reference's gpu_* block) ----
    _P("tpu_use_dp", bool, False),          # f64-emulated histograms vs f32
    _P("tpu_num_devices", int, 0),           # 0 = all local devices
    _P("tpu_mesh_axis", str, "data"),        # mesh axis name for row sharding
    _P("tpu_rows_per_chunk", int, 0),        # 0 = auto; histogram kernel chunking
    _P("tpu_histogram_impl", str, "auto"),   # auto | xla | pallas
    _P("tpu_donate_buffers", bool, True),
    _P("tpu_window_chunk", int, 0),          # 0 = auto; partitioned-grower chunk rows
    _P("tpu_hist_dtype", str, "auto"),       # auto | f32 | f64 | bf16x2
    #                                        # (auto: f64 bins on CPU —
    #                                        # reference double hist_t —
    #                                        # bf16x2 MXU on TPU)
    _P("tpu_pack_impl", str, "sort"),        # sort | matmul (partition pack)
    _P("tpu_scan_impl", str, "auto"),        # auto | xla | pallas (split scan)
    _P("tpu_persist_scan", str, "auto"),     # auto | off | force (persistent-payload scan; force = XLA kernel emulation off-TPU)
    _P("tpu_level_grow", str, "auto"),       # auto | off (level-parallel persist growth: one fused program per tree level when max_depth is set)
    _P("feature_pre_filter", bool, True),
    _P("force_col_wise", bool, False),       # CPU memory-layout hint; no-op
    _P("force_row_wise", bool, False),       # on TPU (HBM layout is fixed)
    _P("max_bin_by_feature", list, []),
    _P("predict_disable_shape_check", bool, False),
    _P("tpu_4bit_packing", bool, True),      # nibble-pack <=16-bin groups in HBM
    _P("tpu_telemetry", str, "off"),         # off | timers | trace (telemetry/)
    _P("telemetry_out", str, ""),            # Chrome-trace/metrics path base
    # ---- inference subsystem (predict/) ----
    _P("predict_device", str, "cpu",         # cpu = numpy walk (default),
       ("predict_backend",)),                # tpu = compiled device runtime
    _P("tpu_predict_dtype", str, "f64"),     # f64 (exact parity) | f32
    _P("tpu_predict_min_batch", int, 256, lo=1),   # serve bucket ladder
    _P("tpu_predict_max_batch", int, 65536, lo=1),  # bounds (pow2-rounded)
    # ---- async serving subsystem (serving/) ----
    _P("tpu_serve_async", bool, False),      # task=predict via the async
    #                                        # continuous-batching server
    _P("tpu_serve_quant", str, "none"),      # none | f16 (certified) |
    #                                        # int8 (refused by cert)
    _P("tpu_serve_max_wait_ms", float, 5.0, lo=0.0),  # deadline budget a
    #                                        # sub-bucket batch may wait
    #                                        # to coalesce (SLO-derived)
    _P("tpu_multival", str, "auto"),         # auto | force | off: ELL row-
    #                                        # sparse device layout (the
    #                                        # MultiValBin/SparseBin analog)
    # ---- multi-model subsystem (multimodel/) ----
    _P("tpu_cv", str, "auto"),               # auto | device | off: engine.cv
    #                                        # folds as lanes of the batched
    #                                        # driver over one shared layout
    # ---- resilience subsystem (resilience/) ----
    # snapshot_freq (reference save_period) above gates HOW OFTEN; these
    # gate WHERE full training-state checkpoints land and how many stay
    _P("checkpoint_dir", str, "", ("checkpoint_directory",)),
    _P("checkpoint_keep", int, 3, lo=1),
    _P("tpu_fault_plan", str, ""),           # deterministic fault injection
    #                                        # (kill@iter= / drop_collective@
    #                                        # round= / corrupt_checkpoint@n=
    #                                        # / stall@ / resize@ /
    #                                        # corrupt_hist@round=;rank=)
    _P("tpu_collective_timeout", float, 300.0, lo=0.0),  # DCN host-
    _P("tpu_collective_retries", int, 2, lo=0),          # collective guard
    _P("tpu_collective_backoff", float, 0.25, lo=0.0),   # (resilience/retry)
    _P("tpu_collective_soft_timeout", float, 0.0, lo=0.0),  # straggler
    #                                        # watchdog soft deadline
    #                                        # (0 = auto: timeout / 4)
    # ---- runtime numerics sentinel (telemetry/health, parallel/
    # fingerprint): the runtime twin of the quant_certify static audit
    _P("tpu_numerics_stats", str, "auto"),   # auto | off: device-side
    #                                        # NaN/Inf counters + split-
    #                                        # margin histogram in the
    #                                        # persist scan carry
    _P("tpu_health_abort", str, ""),         # ""=report-only, or all/
    #                                        # comma list of anomaly kinds
    #                                        # (nonfinite_metric /
    #                                        # margin_collapse /
    #                                        # stall_burst) that abort
    _P("tpu_divergence_probe", str, "auto"),  # auto | on | off: per-
    #                                        # iteration cross-rank
    #                                        # fingerprint compare in the
    #                                        # distributed loop (auto =
    #                                        # only with >1 process; on
    #                                        # forces the world=1 short-
    #                                        # circuit path too)
    # ---- communication-efficient distributed exchange (ROADMAP item 2)
    _P("tpu_hist_quant", str, "off"),        # off | int16: quantize the
    #                                        # cross-device histogram-
    #                                        # plane reductions to int16
    #                                        # with rank-uniform seeded
    #                                        # stochastic rounding; the
    #                                        # spec must pass the
    #                                        # quant_certify certificate
    #                                        # (int8 is refused there)
    _P("tpu_comm_overlap", str, "auto"),     # auto | off: double-buffer
    #                                        # the level program's plane
    #                                        # reductions as two staged
    #                                        # half-batches (comm of half
    #                                        # A overlaps compute of half
    #                                        # B; bit-identical either
    #                                        # way)
]

_BY_NAME: Dict[str, _P] = {p.name: p for p in PARAMS}
_ALIAS2NAME: Dict[str, str] = {}
for _p in PARAMS:
    for _a in _p.aliases:
        _ALIAS2NAME[_a] = _p.name

# objective aliases the reference resolves inside ParseObjectiveAlias
_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2",
    "regression": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg",
    "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "+", "yes", "y", "on"):
        return True
    if s in ("false", "0", "-", "no", "n", "off"):
        return False
    Log.fatal("Cannot parse '%s' as bool" % (v,))


def _parse_vector(v: Any, elem) -> list:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [elem(x) for x in v]
    return [elem(x) for x in str(v).replace(",", " ").split()]


def kv2map(args: List[str]) -> Dict[str, str]:
    """Parse CLI-style 'key=value' tokens (reference Config::KV2Map, config.h:79)."""
    out: Dict[str, str] = {}
    for arg in args:
        arg = arg.strip()
        if not arg or arg.startswith("#"):
            continue
        if "=" not in arg:
            Log.warning("Unknown parameter format '%s', ignored", arg)
            continue
        k, v = arg.split("=", 1)
        k, v = k.strip(), v.split("#", 1)[0].strip()
        if k in out and out[k] != v:
            Log.warning("Duplicate parameter '%s': using first value '%s'", k, out[k])
            continue
        out[k] = v
    return out


def alias_transform(params: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve aliases to canonical names; canonical key wins over alias
    (reference ParameterAlias::KeyAliasTransform, config.h:979)."""
    out: Dict[str, Any] = {}
    aliased: Dict[str, Tuple[str, Any]] = {}
    for k, v in params.items():
        if k in _BY_NAME:
            out[k] = v
        elif k in _ALIAS2NAME:
            name = _ALIAS2NAME[k]
            if name in aliased:
                Log.warning("Parameter '%s' and '%s' are aliases; using '%s'",
                            aliased[name][0], k, aliased[name][0])
            else:
                aliased[name] = (k, v)
        else:
            # unknown keys are kept verbatim (reference passes them through too)
            out[k] = v
    for name, (_, v) in aliased.items():
        if name not in out:
            out[name] = v
    return out


class Config:
    """Typed parameter bag with LightGBM semantics.

    Construct from a dict (Python API) or list of "k=v" strings (CLI). Unknown
    keys are stored in `extra` and carried along untouched.
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None, **kwargs):
        merged = dict(params or {})
        merged.update(kwargs)
        merged = alias_transform(merged)
        self.extra: Dict[str, Any] = {}
        for p in PARAMS:
            setattr(self, p.name, self._coerce(p, merged.get(p.name, p.default)))
        for k, v in merged.items():
            if k not in _BY_NAME:
                self.extra[k] = v
        self._post_process(merged)

    # -- parsing -----------------------------------------------------------
    def _coerce(self, p: _P, v: Any) -> Any:
        if v is None and p.type != "opt_int":
            v = p.default
        try:
            if p.type is bool:
                v = _parse_bool(v)
            elif p.type is int:
                v = int(float(v))
            elif p.type is float:
                v = float(v)
            elif p.type is str:
                v = str(v)
            elif p.type == "opt_int":
                v = None if v in (None, "", "None") else int(float(v))
            elif p.type == "vint":
                v = _parse_vector(v, lambda x: int(float(x)))
            elif p.type == "vdouble":
                v = _parse_vector(v, float)
            elif p.type == "vstr":
                v = _parse_vector(v, str) if not isinstance(v, (list, tuple)) \
                    else [str(x) for x in v]
        except (TypeError, ValueError):
            Log.fatal("Cannot parse parameter %s=%r" % (p.name, v))
        if p.lo is not None and isinstance(v, (int, float)):
            if (p.lo_excl and v <= p.lo) or (not p.lo_excl and v < p.lo):
                Log.fatal("Parameter %s should be %s %s, got %s"
                          % (p.name, ">" if p.lo_excl else ">=", p.lo, v))
        if p.hi is not None and isinstance(v, (int, float)) and v > p.hi:
            Log.fatal("Parameter %s should be <= %s, got %s" % (p.name, p.hi, v))
        return v

    def _post_process(self, merged: Dict[str, Any]) -> None:
        # objective/boosting/metric canonicalization
        obj = str(self.objective).lower()
        if obj in _OBJECTIVE_ALIASES:
            self.objective = _OBJECTIVE_ALIASES[obj]
        booster = str(self.boosting).lower()
        _boost_alias = {"gbdt": "gbdt", "gbrt": "gbdt", "gbm": "gbdt",
                        "dart": "dart", "goss": "goss",
                        "rf": "rf", "random_forest": "rf"}
        if booster in _boost_alias:
            self.boosting = _boost_alias[booster]
        metrics = []
        for m in self.metric:
            ml = str(m).strip().lower()
            if ml == "":
                continue
            metrics.append(_METRIC_ALIASES.get(ml, ml))
        # dedupe keeping order
        seen = set()
        self.metric = [m for m in metrics if not (m in seen or seen.add(m))]
        # seed cascade (reference config.cpp: seed overrides sub-seeds)
        if self.seed is not None:
            self.data_random_seed = self.seed + 1
            self.bagging_seed = self.seed + 2
            self.drop_seed = self.seed + 3
            self.feature_fraction_seed = self.seed + 4
            self.extra_seed = self.seed + 5
            self.objective_seed = self.seed + 6
        tl = str(self.tree_learner).lower()
        _tl_alias = {"serial": "serial",
                     "feature": "feature", "feature_parallel": "feature",
                     "data": "data", "data_parallel": "data",
                     "voting": "voting", "voting_parallel": "voting"}
        if tl not in _tl_alias:
            Log.fatal("Unknown tree learner type %s" % tl)
        self.tree_learner = _tl_alias[tl]
        dev = str(self.device_type).lower()
        if dev not in ("cpu", "gpu", "tpu"):
            Log.fatal("Unknown device type %s" % dev)
        self.device_type = dev
        pdev = str(self.predict_device).lower()
        if pdev not in ("cpu", "tpu"):
            Log.fatal("Unknown predict_device %s (expected cpu|tpu)" % pdev)
        self.predict_device = pdev
        pdt = str(self.tpu_predict_dtype).lower()
        if pdt not in ("f64", "f32", "float64", "float32"):
            Log.fatal("Unknown tpu_predict_dtype %s (expected f64|f32)" % pdt)
        self.tpu_predict_dtype = "f32" if pdt in ("f32", "float32") else "f64"
        if self.tpu_predict_max_batch < self.tpu_predict_min_batch:
            Log.fatal("tpu_predict_max_batch < tpu_predict_min_batch")
        sq = str(self.tpu_serve_quant).lower()
        if sq in ("", "false", "0", "off"):
            sq = "none"
        # int8 parses here but is refused at registry load by the
        # quant_certify certificate (serving/quantized.py) with the
        # bound named in the error — same seam as tpu_hist_quant
        if sq not in ("none", "f16", "float16", "int8"):
            Log.fatal("Unknown tpu_serve_quant %s (expected "
                      "none|f16|int8)" % sq)
        self.tpu_serve_quant = "f16" if sq == "float16" else sq
        if self.tpu_serve_async and self.predict_device != "tpu":
            # asking for the async service loop IS asking for the device
            # runtime; without this the serving knobs silently fall
            # through to the host walk
            Log.info("tpu_serve_async=true implies predict_device=tpu")
            self.predict_device = "tpu"
        hq = str(self.tpu_hist_quant).lower()
        if hq in ("", "false", "0"):
            hq = "off"
        # int8 parses here but is refused at learner build by the
        # quant_certify certificate (parallel/distributed.
        # resolve_hist_quant) with the bound named in the error
        if hq not in ("off", "int16", "int8"):
            Log.fatal("Unknown tpu_hist_quant %s (expected off|int16)"
                      % self.tpu_hist_quant)
        self.tpu_hist_quant = hq
        if self.boosting == "rf":
            if not (self.bagging_freq > 0 and 0.0 < self.bagging_fraction < 1.0):
                Log.fatal("Random forest needs bagging_freq > 0 and "
                          "bagging_fraction in (0, 1)")

    # -- derived flags (reference config.h:910-911) ------------------------
    @property
    def is_parallel(self) -> bool:
        return self.num_machines > 1 or self.tree_learner != "serial"

    @property
    def is_data_based_parallel(self) -> bool:
        return self.tree_learner in ("data", "voting")

    # -- misc --------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d = {p.name: getattr(self, p.name) for p in PARAMS}
        d.update(self.extra)
        return d

    @classmethod
    def from_cli_args(cls, argv: List[str]) -> "Config":
        kv = kv2map(argv)
        if "config" in kv and kv["config"]:
            file_kv: Dict[str, str] = {}
            with open(kv["config"]) as f:
                file_kv = kv2map(f.read().splitlines())
            # CLI args take precedence over config file (application.cpp:49-82)
            file_kv.update(kv)
            kv = file_kv
        return cls(kv)


def params_to_config(params: Optional[Dict[str, Any]]) -> Config:
    if isinstance(params, Config):
        return params
    return Config(params or {})
