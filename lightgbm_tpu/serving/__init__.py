"""Async serving subsystem: the admission layer above :mod:`predict`.

Three pieces (ROADMAP serving item; docs/COMPONENTS.md "Serving"):

* :mod:`server`    — :class:`AsyncBatchServer`: async request queue with
  continuous batching over the power-of-two bucket ladder, deadline-
  aware partial flush, per-request futures, mesh row-sharding for large
  admitted batches;
* :mod:`registry`  — :class:`ModelRegistry`: named model slots, atomic
  hot-swap (admission-time snapshots: in-flight requests finish on the
  old model, zero drops), bit-exact rollback, loads from Booster /
  model text / resilience checkpoints;
* :mod:`quantized` — the f16 value-grid admission seam: quantized
  ensembles serve only under a passing ``quant_certify`` certificate
  against ``PREDICT_REL_BUDGET``; refusals (int8) name the certificate.

The sync :class:`predict.serve.BatchServer` remains the simple
one-caller path; this package is the shared-service path ("heavy
traffic from millions of users").
"""
from .quantized import QuantRefusedError, quantized_for_serving
from .registry import ModelRegistry, ModelSlot
from .server import AsyncBatchServer, ServeFuture, ServingError

__all__ = ["AsyncBatchServer", "ServeFuture", "ServingError",
           "ModelRegistry", "ModelSlot", "QuantRefusedError",
           "quantized_for_serving"]
