"""Multi-model registry with atomic hot-swap and rollback.

Named model slots, each pinning one compiled :class:`TPUPredictor`
(HBM-resident ensemble tensors). The ACTIVE slot is a single reference
the admission path snapshots per request — swapping is one assignment
under the registry lock, so:

  * requests admitted before the swap finish on the model they were
    admitted against (the async server pins the predictor snapshot at
    admission; a request can never mix two models' trees);
  * requests admitted after the swap route to the new model;
  * nothing is ever dropped — there is no draining barrier, the old
    predictor stays alive (and HBM-resident) until the last in-flight
    batch against it finalizes and Python releases the reference.

Load paths: an in-memory Booster, a model file / model string (the
reference text format), or a resilience snapshot
(:func:`resilience.model_text_from_checkpoint` — kind="model"
checkpoints store the model text CRC-validated, so a torn file is a
clean error, never a half-loaded slot). Quantized variants go through
:mod:`serving.quantized`: certify-then-build, refusal leaves the
previously active slot serving.

``rollback()`` restores the previously active slot — bit-exact, because
the old predictor object (same HBM tensors, same executables) is kept,
not reloaded. Every swap/rollback bumps ``serving::swap`` /
``serving::rollback`` and leaves a flight-note for the postmortem ring.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..predict.compile import compile_ensemble
from ..predict.runtime import TPUPredictor
from ..telemetry import events as telemetry
from ..telemetry import flight
from .quantized import QUANT_NONE, quantized_for_serving

C_SWAP = "serving::swap"
C_ROLLBACK = "serving::rollback"
C_LOAD = "serving::model_load"


class ModelSlot:
    """One named, immutable registry entry."""

    __slots__ = ("name", "predictor", "quant", "certificate", "source",
                 "num_trees", "loaded_at")

    def __init__(self, name: str, predictor: TPUPredictor, quant: str,
                 certificate: Optional[dict], source: str):
        self.name = name
        self.predictor = predictor
        self.quant = quant
        self.certificate = certificate
        self.source = source
        self.num_trees = predictor.ensemble.num_trees
        self.loaded_at = time.time()

    def describe(self) -> dict:
        d = {"name": self.name, "quant": self.quant,
             "source": self.source, "num_trees": self.num_trees,
             "loaded_at": self.loaded_at}
        if self.certificate is not None:
            d["certificate"] = {
                "name": self.certificate["spec"].get("name"),
                "bound": self.certificate["bound"],
                "budget": self.certificate["budget"],
                "margin": self.certificate.get("margin")}
        return d


class ModelRegistry:
    """Named slots + one atomic active pointer (see the module doc)."""

    def __init__(self, dtype: str = "f64", min_rows: int = 128,
                 params: Optional[dict] = None):
        self.dtype = dtype
        self.min_rows = int(min_rows)
        self.params = dict(params or {})
        self._slots: Dict[str, ModelSlot] = {}
        self._active: Optional[ModelSlot] = None
        self._previous: Optional[ModelSlot] = None
        self._swaps = 0
        self._lock = threading.RLock()

    # -- loading -------------------------------------------------------
    def load(self, name: str, booster=None, model_file: str = None,
             model_str: str = None, checkpoint: str = None,
             quant: str = QUANT_NONE, activate: bool = False) -> ModelSlot:
        """Compile a model into the named slot (exactly one source).

        Certification happens BEFORE the slot is written: a refused
        quantization (:class:`serving.quantized.QuantRefusedError`)
        leaves the registry — including the active pointer — exactly as
        it was. ``activate=True`` swaps the new slot in atomically; the
        first successful load activates unconditionally so a fresh
        registry is immediately servable.
        """
        sources = [s for s in (booster, model_file, model_str, checkpoint)
                   if s is not None]
        if len(sources) != 1:
            raise ValueError(
                "load() needs exactly one of booster/model_file/"
                "model_str/checkpoint (got %d)" % len(sources))
        if checkpoint is not None:
            from ..resilience import model_text_from_checkpoint
            model_str, _meta = model_text_from_checkpoint(checkpoint)
            source = "checkpoint:%s" % checkpoint
        elif model_file is not None:
            source = "file:%s" % model_file
        elif model_str is not None:
            source = "string"
        else:
            source = "booster"
        if booster is None:
            from ..basic import Booster
            booster = Booster(params=self.params, model_file=model_file,
                              model_str=model_str)
        gb = booster._booster
        # _used_models materializes any pending async trees first — a
        # live training booster is loadable mid-run
        models = gb._used_models(0, -1)
        ens = compile_ensemble(models, gb.num_tree_per_iteration,
                               gb.average_output, gb.max_feature_idx)
        ens, cert = quantized_for_serving(ens, quant)
        pred = TPUPredictor(ens, gb.objective, dtype=self.dtype,
                            min_rows=self.min_rows)
        slot = ModelSlot(name, pred, quant or QUANT_NONE, cert, source)
        telemetry.count(C_LOAD, 1, category="serving")
        with self._lock:
            self._slots[name] = slot
            if activate or self._active is None:
                self._swap_locked(slot, why="load")
        return slot

    # -- swap / rollback ----------------------------------------------
    def _swap_locked(self, slot: ModelSlot, why: str) -> None:
        prev = self._active
        # the atomic flip: one reference assignment under the lock —
        # admission snapshots (resolve()) see strictly-before or
        # strictly-after, never a mix
        self._active = slot
        self._previous = prev
        self._swaps += 1
        telemetry.count(C_SWAP, 1, category="serving")
        flight.note("serving::swap", model=slot.name, why=why,
                    quant=slot.quant,
                    prev=prev.name if prev is not None else None)

    def swap(self, name: str) -> ModelSlot:
        """Atomically make the named slot active; returns it."""
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                raise KeyError("no model slot %r (have: %s)"
                               % (name, sorted(self._slots) or "none"))
            self._swap_locked(slot, why="swap")
            return slot

    def rollback(self) -> ModelSlot:
        """Restore the previously active slot — the same predictor
        object, so post-rollback scores are bit-exact with pre-swap."""
        with self._lock:
            if self._previous is None:
                raise RuntimeError(
                    "nothing to roll back to (fewer than two "
                    "activations so far)")
            slot = self._previous
            self._swap_locked(slot, why="rollback")
            telemetry.count(C_ROLLBACK, 1, category="serving")
            return slot

    # -- resolution ----------------------------------------------------
    def resolve(self, name: Optional[str] = None) -> TPUPredictor:
        """Predictor snapshot for admission: the active slot's (or a
        named slot's) predictor, captured once — the caller keeps using
        this exact object however many swaps happen afterwards."""
        with self._lock:
            slot = self._active if name is None else self._slots.get(name)
            if slot is None:
                raise RuntimeError(
                    "no active model in the registry"
                    if name is None else "no model slot %r" % name)
            return slot.predictor

    def active(self) -> Optional[ModelSlot]:
        with self._lock:
            return self._active

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def drop(self, name: str) -> None:
        """Remove a slot (refused while active — swap away first)."""
        with self._lock:
            if self._active is not None and self._active.name == name:
                raise RuntimeError("cannot drop the active slot %r"
                                   % name)
            self._slots.pop(name, None)
            if self._previous is not None and self._previous.name == name:
                self._previous = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": {n: s.describe()
                          for n, s in self._slots.items()},
                "active": (self._active.name
                           if self._active is not None else None),
                "previous": (self._previous.name
                             if self._previous is not None else None),
                "swaps": self._swaps,
            }
