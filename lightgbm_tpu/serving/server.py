"""Async request queue with continuous batching over the bucket ladder.

The synchronous :class:`predict.serve.BatchServer` answers one request
per device program: a burst of K small requests costs K program
invocations, each mostly padding. This server puts an admission queue in
front of the same machinery and runs a dedicated service loop that

  * **admits while a batch is in flight** — dispatch is jax-async (the
    device array comes back before the work finishes), so the loop
    builds the next coalesced batch while the chips chew the current
    one, and only blocks at the one deliberate host sync per batch
    (:meth:`TPUPredictor.finalize_padded`);
  * **coalesces** the FIFO prefix of compatible requests (same model
    snapshot, same raw flag, same feature width) into ONE padded
    power-of-two bucket — the ladder, chunking and mesh row-sharding
    (``shard_min_rows``, via :func:`predict.serve.place_padded`) are
    exactly the sync server's, so the compile bound is unchanged;
  * **flushes deadline-aware** — a sub-bucket batch is held for
    coalescing only while the device is busy or until the oldest
    request has waited ``max_wait`` (the SLO-derived budget); then it is
    flushed PARTIAL rather than starved. A full bucket flushes
    immediately; an idle device with a warm bucket flushes immediately.

Callers get a :class:`ServeFuture` per request and block only on their
own rows. Model identity is pinned at ADMISSION (a snapshot out of the
:class:`serving.registry.ModelRegistry`): an atomic hot-swap lands
between requests, never inside one — in-flight and queued requests
finish on the model they were admitted against, new admissions route to
the new model, and nothing is dropped.

Request arrival-time SLO accounting mirrors the sync server: queue wait
is admission -> service start, e2e is admission -> answer, both into
instance histograms (``stats()``) and the global telemetry registry
(``serving::*`` families, exported to Prometheus).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np

import jax

from ..predict.runtime import TPUPredictor, _next_pow2
from ..predict.serve import build_mesh, place_padded
from ..telemetry import events as telemetry
from ..telemetry import histo as telemetry_histo
from ..telemetry.histo import Histogram

C_REQUESTS = "serving::requests"
C_BATCHES = "serving::batches"
C_COALESCED = "serving::coalesced_requests"
C_FLUSH_FULL = "serving::flush_full"
C_FLUSH_DEADLINE = "serving::flush_deadline"
C_FLUSH_IDLE = "serving::flush_idle"
C_ERRORS = "serving::request_errors"
H_E2E = "serving::e2e_latency"
H_QUEUE = "serving::queue_wait"
H_QDEPTH = "serving::queue_depth"
H_BATCH_ROWS = "serving::batch_rows"

# service-loop poll bound: how long the loop sleeps when the queue is
# empty; also the deadline-check granularity while holding a partial
# batch (a fraction of max_wait, floored so an idle server stays cheap)
_MIN_POLL_S = 0.0005


class ServingError(RuntimeError):
    pass


class ServeFuture:
    """Per-request handle: the caller blocks only on its own rows.

    Oversized requests (rows > max_batch) are admitted as several
    chunked parts sharing one future; parts re-assemble in order."""

    __slots__ = ("_event", "_parts", "_missing", "_exc", "_lock")

    def __init__(self, parts: int = 1):
        self._event = threading.Event()
        self._parts: List[Optional[np.ndarray]] = [None] * parts
        self._missing = parts
        self._exc: Optional[BaseException] = None
        self._lock = threading.Lock()

    def _set_part(self, index: int, value: np.ndarray) -> None:
        with self._lock:
            if self._parts[index] is None:
                self._parts[index] = value
                self._missing -= 1
            if self._missing <= 0:
                self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        with self._lock:
            if self._exc is None:
                self._exc = exc
            self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serving request not finished within %r s"
                               % timeout)
        if self._exc is not None:
            raise self._exc
        if len(self._parts) == 1:
            return self._parts[0]
        return np.concatenate(self._parts, axis=0)


class _Request:
    """One admitted chunk: rows + routing snapshot + its future part."""

    __slots__ = ("X", "n", "raw_score", "predictor", "arrival_t",
                 "future", "part")

    def __init__(self, X, n, raw_score, predictor, arrival_t, future,
                 part):
        self.X = X
        self.n = n
        self.raw_score = raw_score
        self.predictor = predictor
        self.arrival_t = arrival_t
        self.future = future
        self.part = part


class _Inflight:
    """One dispatched batch awaiting its finalize sync."""

    __slots__ = ("out_dev", "group", "rows", "predictor", "raw_score")

    def __init__(self, out_dev, group, rows, predictor, raw_score):
        self.out_dev = out_dev
        self.group = group
        self.rows = rows
        self.predictor = predictor
        self.raw_score = raw_score


class AsyncBatchServer:
    """Continuous-batching server over one model source.

    ``model`` is either a fixed :class:`TPUPredictor` or a
    :class:`serving.registry.ModelRegistry` (hot-swap: each request
    snapshots the then-active predictor at admission).

    ``max_wait_ms`` is the deadline budget a sub-bucket batch may spend
    waiting to coalesce (derive it from the SLO: a p99 budget of B ms
    splits into wait + service, so B/4 is a sane default split).
    """

    def __init__(self, model, min_batch: int = 256,
                 max_batch: int = 1 << 16, shard_min_rows: int = 8192,
                 devices=None, max_wait_ms: float = 5.0):
        if max_batch < min_batch:
            raise ValueError("max_batch %d < min_batch %d"
                             % (max_batch, min_batch))
        self._registry = model if not isinstance(model, TPUPredictor) \
            else None
        self._fixed = model if isinstance(model, TPUPredictor) else None
        self.min_batch = _next_pow2(max(int(min_batch), 1))
        self.max_batch = _next_pow2(int(max_batch))
        self.shard_min_rows = int(shard_min_rows)
        self.max_wait = max(float(max_wait_ms), 0.0) / 1e3
        self.devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        self._mesh = build_mesh(self.devices)
        self._poll = max(self.max_wait / 4.0, _MIN_POLL_S)
        # admission state (guarded by _cond's lock)
        self._cond = threading.Condition()
        self._pending: deque = deque()
        self._depth = 0              # admitted, not yet answered
        self._qdepth_max = 0
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        # in-flight pipeline (service-loop private, depth <= 2: one
        # batch on device, one being built/finalized)
        self._inflight: deque = deque()
        # instance-local stats (work with telemetry off, like the sync
        # server's)
        self._requests = 0
        self._batches = 0
        self._flushes = {"full": 0, "deadline": 0, "idle": 0}
        self._errors = 0
        self._compiled_buckets = set()
        self._h_e2e = Histogram(H_E2E, unit="s", category="serving")
        self._h_queue = Histogram(H_QUEUE, unit="s", category="serving")
        self._h_qdepth = Histogram(H_QDEPTH, unit="req",
                                   category="serving")
        self._h_batch_rows = Histogram(H_BATCH_ROWS, lo=1.0, hi=1e7,
                                       unit="rows", category="serving")

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "AsyncBatchServer":
        if self._thread is None or not self._thread.is_alive():
            self._stopping = False
            self._thread = threading.Thread(
                target=self._loop, name="serving-loop", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the loop; with drain (default) every queued request is
        answered first — the zero-drop guarantee covers shutdown too."""
        with self._cond:
            self._stopping = True
            if not drain:
                err = ServingError("server stopped without drain")
                while self._pending:
                    self._pending.popleft().future._set_exception(err)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "AsyncBatchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission -----------------------------------------------------
    def _resolve(self) -> TPUPredictor:
        if self._fixed is not None:
            return self._fixed
        return self._registry.resolve()

    def submit(self, X, raw_score: bool = False,
               arrival_t: Optional[float] = None) -> ServeFuture:
        """Admit one request; returns its future. The model snapshot is
        taken HERE: whatever swap lands later, this request's rows run
        on the model that was active at admission. Requests larger than
        max_batch are chunked into parts behind one future."""
        arrival = arrival_t if arrival_t is not None \
            else time.perf_counter()
        X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[0] == 0:
            raise ValueError("empty request")
        predictor = self._resolve()
        n_parts = (X.shape[0] + self.max_batch - 1) // self.max_batch
        future = ServeFuture(parts=n_parts)
        reqs = [_Request(X[i * self.max_batch:(i + 1) * self.max_batch],
                         min(self.max_batch,
                             X.shape[0] - i * self.max_batch),
                         bool(raw_score), predictor, arrival, future, i)
                for i in range(n_parts)]
        with self._cond:
            if self._stopping:
                raise ServingError("server is stopped")
            self._pending.extend(reqs)
            self._depth += 1
            if self._depth > self._qdepth_max:
                self._qdepth_max = self._depth
            depth = self._depth
            self._requests += 1
            # instance stats share _cond with admission state: submit()
            # races the service loop's _dispatch/_finalize records
            self._h_qdepth.record(float(depth))
            self._cond.notify()
        telemetry.count(C_REQUESTS, 1, category="serving")
        telemetry_histo.observe(H_QDEPTH, float(depth), unit="req",
                                category="serving")
        return future

    def predict(self, X, raw_score: bool = False,
                arrival_t: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait on this request only."""
        return self.submit(X, raw_score=raw_score,
                           arrival_t=arrival_t).result()

    # -- service loop ---------------------------------------------------
    def _loop(self) -> None:
        # the loop body is helper calls only: the deliberate per-batch
        # host sync lives in _finalize (graftlint JG002 polices this
        # file — no sync may sit lexically in the hot loop)
        while self._step():
            pass

    def _step(self) -> bool:
        group = self._admit_wave()
        if group:
            self._inflight.append(self._dispatch(group))
        if self._inflight and (len(self._inflight) >= 2 or not group):
            self._finalize(self._inflight.popleft())
        with self._cond:
            if self._stopping and not self._pending \
                    and not self._inflight:
                return False
        return True

    def _admit_wave(self) -> Optional[List[_Request]]:
        """Take the FIFO prefix of coalescible requests when the flush
        policy says go; None to hold (or when the queue is idle)."""
        with self._cond:
            if not self._pending and not self._inflight \
                    and not self._stopping:
                self._cond.wait(timeout=self._poll)
            if not self._pending:
                return None
            head = self._pending[0]
            key = (id(head.predictor), head.raw_score, head.X.shape[1])
            rows = 0
            take = 0
            for r in self._pending:
                if (id(r.predictor), r.raw_score, r.X.shape[1]) != key \
                        or rows + r.n > self.max_batch:
                    break
                rows += r.n
                take += 1
            full = rows >= self.max_batch or take < len(self._pending)
            waited = time.perf_counter() - head.arrival_t
            deadline = waited >= self.max_wait
            idle = not self._inflight
            if self._stopping:
                cause = "idle"
            elif full:
                cause = "full"
            elif deadline:
                cause = "deadline"
            elif idle and rows >= self.min_batch:
                cause = "idle"
            else:
                # hold: device busy, or a sub-bucket batch still inside
                # its coalescing window — the deadline branch above
                # guarantees no request waits past max_wait. With an
                # idle device, sleep out (a slice of) the window on the
                # condition instead of spinning; a new arrival wakes us.
                if idle:
                    self._cond.wait(timeout=min(
                        max(self.max_wait - waited, 0.0) + 1e-4,
                        self._poll))
                return None
            group = [self._pending.popleft() for _ in range(take)]
            self._flushes[cause] += 1
        telemetry.count({"full": C_FLUSH_FULL,
                         "deadline": C_FLUSH_DEADLINE,
                         "idle": C_FLUSH_IDLE}[cause], 1,
                        category="serving")
        return group

    def _dispatch(self, group: List[_Request]) -> _Inflight:
        """Pad + place + queue one coalesced batch on device (async —
        returns before the device finishes)."""
        pred = group[0].predictor
        raw = group[0].raw_score
        rows = sum(r.n for r in group)
        bucket = min(max(_next_pow2(rows), self.min_batch),
                     self.max_batch)
        Xp = np.zeros((bucket, group[0].X.shape[1]), dtype=np.float64)
        off = 0
        t_svc = time.perf_counter()
        for r in group:
            Xp[off:off + r.n] = r.X
            off += r.n
        self._record_queue_waits(group, t_svc)
        X_dev, _sharded = place_padded(Xp, pred._dtype, self._mesh,
                                       self.devices, self.shard_min_rows)
        out_dev = pred.dispatch_padded(X_dev, raw_score=raw)
        with self._cond:
            # service-loop stats vs submit()'s _h_qdepth record and
            # stats() snapshots — all instance stats live under _cond
            for r in group:
                self._h_queue.record(max(t_svc - r.arrival_t, 0.0))
            self._compiled_buckets.add((id(pred), bucket))
            self._batches += 1
            self._h_batch_rows.record(float(rows))
        telemetry.count(C_BATCHES, 1, category="serving")
        telemetry.count(C_COALESCED, len(group), category="serving")
        telemetry_histo.observe(H_BATCH_ROWS, float(rows), unit="rows",
                                category="serving")
        return _Inflight(out_dev, group, rows, pred, raw)

    def _record_queue_waits(self, group: List[_Request],
                            t_svc: float) -> None:
        for r in group:
            telemetry_histo.observe(H_QUEUE,
                                    max(t_svc - r.arrival_t, 0.0),
                                    unit="s", category="serving")

    def _finalize(self, inf: _Inflight) -> None:
        """The one host sync per batch: materialize, scatter each
        request's rows to its future, record e2e from arrival."""
        try:
            out = inf.predictor.finalize_padded(inf.out_dev, inf.rows,
                                                raw_score=inf.raw_score)
        except Exception as exc:           # noqa: BLE001 — futures must
            self._fail_group(inf.group, exc)   # never hang on any error
            return
        off = 0
        t_done = time.perf_counter()
        for r in inf.group:
            r.future._set_part(r.part, out[off:off + r.n])
            off += r.n
        self._record_e2e(inf.group, t_done)
        with self._cond:
            for r in inf.group:
                self._h_e2e.record(max(t_done - r.arrival_t, 0.0))
            self._depth -= len({id(r.future) for r in inf.group
                                if r.part == 0})

    def _record_e2e(self, group: List[_Request], t_done: float) -> None:
        for r in group:
            telemetry_histo.observe(H_E2E,
                                    max(t_done - r.arrival_t, 0.0),
                                    unit="s", category="serving")

    def _fail_group(self, group: List[_Request],
                    exc: BaseException) -> None:
        telemetry.count(C_ERRORS, len(group), category="serving")
        for r in group:
            r.future._set_exception(exc)
        with self._cond:
            self._errors += len(group)
            self._depth -= len({id(r.future) for r in group
                                if r.part == 0})

    # -- stats ----------------------------------------------------------
    def stats(self) -> dict:
        """Telemetry-independent serving stats, the async analog of
        BatchServer.stats() (same SLO shortcut keys)."""
        with self._cond:
            # one consistent snapshot: the service loop mutates all of
            # these under _cond, so reading them here cannot tear (or
            # hit a set-changed-during-iteration on _compiled_buckets)
            d = {
                "requests": self._requests,
                "batches": self._batches,
                "coalesce_ratio": (self._requests / self._batches
                                   if self._batches else 0.0),
                "flushes": dict(self._flushes),
                "errors": self._errors,
                "depth": self._depth,
                "qdepth_max": self._qdepth_max,
                "buckets_compiled": sorted(b for _, b in
                                           self._compiled_buckets),
                "latency_p50": self._h_e2e.percentile(0.50),
                "latency_p99": self._h_e2e.percentile(0.99),
                "queue_wait_p99": self._h_queue.percentile(0.99),
                "queue_wait_max": (self._h_queue.vmax
                                   if self._h_queue.count else None),
                "max_wait": self.max_wait,
                "latency": self._h_e2e.to_dict(with_buckets=False),
                "queue_wait": self._h_queue.to_dict(with_buckets=False),
                "batch_rows": self._h_batch_rows.to_dict(
                    with_buckets=False),
            }
        # registry.stats() takes the registry lock (which edges into
        # the telemetry locks); call it outside _cond to keep the
        # acquisition-order graph a simple fan-out
        if self._registry is not None:
            d["registry"] = self._registry.stats()
        return d
