"""Quantized-ensemble admission seam: certify BEFORE the tensors serve.

ROADMAP item 3's quantized serving variant rides the same
certificate-gated pattern as the int16 histogram collectives (PR 15):
the numerics auditor (:mod:`analysis.quant_audit`) owns the error
algebra, this module owns the REFUSAL — a quantization target whose
certificate bound exceeds the pinned ``PREDICT_REL_BUDGET`` never
reaches the device, and the error names the certificate so the operator
can read the exact bound that failed out of ``--json``'s
``quant_certificate`` block.

The f16 grid (relative error ``2^-11``) certifies with ~2x margin
against the 1e-3 budget; int8 (``1/127`` ~ ``2^-7``) blows it by ~8x
and is refused here — :func:`predict.compile.quantize_ensemble` cannot
even build it, by design. Quantization is a HOST-side value snap: the
jitted traversal still runs at the runtime dtype, so the precision-flow
audit's ``NARROW_OK`` table stays empty and no new jit site appears on
the compile surface.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..analysis import quant_audit
from ..predict.compile import (CompiledEnsemble, quant_spec,
                               quantize_ensemble)
from ..telemetry import events as telemetry
from ..utils.log import LightGBMError

C_QUANT_ADMITTED = "serving::quant_admitted"
C_QUANT_REFUSED = "serving::quant_refused"

QUANT_NONE = "none"
# aliases accepted from config/params; canonical targets are what
# quant_spec names certificates after (leaf_float16 / leaf_int8)
_CANONICAL = {"f16": "float16", "fp16": "float16", "float16": "float16",
              "half": "float16", "int8": "int8"}


class QuantRefusedError(LightGBMError):
    """A quantization target failed (or lacks) certification; the
    message names the certificate and the failing bound. The registry
    guarantees the previously active model keeps serving."""

    def __init__(self, msg: str, certificate: Optional[dict] = None):
        super().__init__(msg)
        self.certificate = certificate


def certify_target(ensemble: CompiledEnsemble, target: str) -> dict:
    """Certificate for serving `ensemble` on the `target` value grid —
    the spec caps come from the actual packed tensors, not the contract
    defaults, so the bound reflects the model being admitted."""
    return quant_audit.certify(quant_spec(ensemble, target=target))


def quantized_for_serving(ensemble: CompiledEnsemble, target: str
                          ) -> Tuple[CompiledEnsemble, Optional[dict]]:
    """(possibly-quantized ensemble, certificate) for a registry load.

    ``target="none"`` passes the ensemble through untouched (no
    certificate needed — nothing was narrowed). Any other target is
    certified FIRST: a failing certificate raises
    :class:`QuantRefusedError` naming it (e.g. ``leaf_int8``), before
    any tensor is built, so refusal costs nothing and cannot leave a
    half-quantized model behind.
    """
    if target in (None, "", QUANT_NONE):
        return ensemble, None
    canonical = _CANONICAL.get(str(target).lower())
    if canonical is None:
        raise QuantRefusedError(
            "unknown quantization target %r (known: none, f16/float16, "
            "int8 — and int8 is refused by its certificate)" % (target,))
    cert = certify_target(ensemble, canonical)
    name = cert["spec"].get("name", "leaf_%s" % canonical)
    if not cert.get("ok", False):
        telemetry.count(C_QUANT_REFUSED, 1, category="serving")
        raise QuantRefusedError(
            "quantized serving refused: certificate %s has bound %.3g > "
            "PREDICT_REL_BUDGET %.3g (%.1fx over) — the model was NOT "
            "swapped in" % (name, cert["bound"], cert["budget"],
                            cert["bound"] / cert["budget"]),
            certificate=cert)
    quantized, _spec = quantize_ensemble(ensemble, target=canonical)
    telemetry.count(C_QUANT_ADMITTED, 1, category="serving")
    return quantized, cert
