"""Training and cross-validation entry points.

TPU-native rebuild of the reference python-package surface: `train`
(python-package/lightgbm/engine.py:18) and `cv` (:375) with the same
observable contract — callback staging/timing via CallbackEnv, alias
precedence for round counts and early stopping, train-set evaluation when
the train set appears among the valid sets, `best_score`/`best_iteration`
population, and stratified/group fold construction. The implementation is
organized around a CallbackRegistry (staged, order-sorted dispatch) and an
EvalPlan (which datasets get evaluated each round, and under what names)
rather than the reference's inline loops; the per-round work itself —
gradients, tree growth, score updates — runs as jitted device programs
behind Booster.update.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import callback
from .basic import Booster, Dataset
from .utils.log import LightGBMError, Log

_ROUND_COUNT_KEYS = (
    "num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees",
    "num_round", "num_rounds", "num_boost_round", "n_estimators")
_STOP_ROUND_KEYS = ("early_stopping_round", "early_stopping_rounds",
                    "early_stopping", "n_iter_no_change")


def _alias_override(params: Dict[str, Any], keys, fallback):
    """Pop the first matching alias out of `params`; params win over the
    keyword argument (reference alias precedence, engine.py:119-155)."""
    for key in keys:
        if key in params:
            Log.warning("Found `%s` in params. Will use it instead of "
                        "argument" % key)
            return int(params.pop(key))
    return fallback


class _CallbackRegistry:
    """Staged callback dispatch.

    Callbacks carry an `order` (implicit ones set their own; user-supplied
    ones default to negative offsets so they fire ahead of implicit ones)
    and a `before_iteration` flag selecting the stage. Dispatch is a stable
    sort by order within each stage.
    """

    def __init__(self, user_callbacks=None):
        self._pre: List = []
        self._post: List = []
        user_callbacks = list(user_callbacks or ())
        for offset, cb in enumerate(user_callbacks):
            cb.__dict__.setdefault("order", offset - len(user_callbacks))
        # identical objects registered twice fire once
        for cb in dict.fromkeys(user_callbacks):
            self.add(cb)

    def add(self, cb) -> None:
        stage = (self._pre if getattr(cb, "before_iteration", False)
                 else self._post)
        stage.append(cb)

    def seal(self) -> None:
        self._pre.sort(key=lambda cb: getattr(cb, "order", 0))
        self._post.sort(key=lambda cb: getattr(cb, "order", 0))

    @property
    def has_pre_stage(self) -> bool:
        return bool(self._pre)

    def fire_pre(self, env: "callback.CallbackEnv") -> None:
        for cb in self._pre:
            cb(env)

    def fire_post(self, env: "callback.CallbackEnv") -> None:
        """May raise callback.EarlyStopException."""
        for cb in self._post:
            cb(env)


class _EvalPlan(collections.namedtuple(
        "_EvalPlan", ["eval_train", "train_name", "attached"])):
    """Which datasets each round evaluates: the train set itself (when the
    caller listed it among valid_sets) plus the attached held-out sets."""

    @classmethod
    def build(cls, train_set: Dataset, valid_sets, valid_names):
        if valid_sets is None:
            return cls(False, "training", [])
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        names = list(valid_names) if valid_names is not None else []
        eval_train = False
        train_name = "training"
        attached: List[Tuple[Dataset, str]] = []
        for pos, ds in enumerate(valid_sets):
            label = names[pos] if pos < len(names) else "valid_%d" % pos
            if ds is train_set:
                eval_train = True
                if pos < len(names):
                    train_name = label
            else:
                if not isinstance(ds, Dataset):
                    raise TypeError("Training only accepts Dataset object")
                attached.append((ds, label))
        return cls(eval_train, train_name, attached)

    def attach(self, booster: Booster, params: Dict[str, Any],
               train_set: Dataset) -> None:
        if self.eval_train:
            booster.set_train_data_name(self.train_name)
        for ds, label in self.attached:
            ds._update_params(params).set_reference(train_set)
            booster.add_valid(ds, label)

    def evaluate(self, booster: Booster, feval) -> List:
        out: List = []
        if self.eval_train:
            out.extend(booster.eval_train(feval))
        out.extend(booster.eval_valid(feval))
        return out

    @property
    def active(self) -> bool:
        return self.eval_train or bool(self.attached)


def _load_init_model(init_model) -> Optional[str]:
    if init_model is None:
        return None
    if isinstance(init_model, Booster):
        # an early-stopped Booster carries its rollback point in
        # best_iteration; continued training must resume from there
        # (model_to_string's default honors it) — the old explicit
        # num_iteration=-1 grafted the over-trained tail trees while
        # best_iteration kept pointing at the truncated model
        return init_model.model_to_string(num_iteration=None)
    with open(init_model) as fh:
        return fh.read()


def _graft_init_model(booster: Booster, model_str: str,
                      train_set: Dataset) -> int:
    """Continued training (reference engine.py:159-165 feeds an
    _InnerPredictor whose cached scores seed the new booster): prepend the
    init model's trees and push their binned-walk predictions into the
    fresh score updater."""
    stump = Booster(model_str=model_str)
    inner = booster._booster
    ntpi = inner.num_tree_per_iteration
    for pos, tree in enumerate(stump._booster.models):
        # loaded trees carry only real-valued thresholds; bind them to the
        # new dataset's bins before the binned walk
        tree.bind_to_dataset(train_set._inner)
        inner.train_score.add_score_np(
            tree.predict_binned(train_set._inner), pos % ntpi)
    inner.models = stump._booster.models + inner.models
    inner.num_init_iteration = stump.current_iteration
    inner.iter = 0
    return stump.current_iteration


def _distributed_raw(ds, cfg, categorical_feature="auto"):
    """(X, label, weight, cat_indices) host arrays of a not-yet-
    constructed Dataset for per-rank sharding; file-backed Datasets load
    through the text reader, matrices through the same pandas/categorical
    coercion the single-host path uses (basic._data_to_2d)."""
    import numpy as np
    from .utils.log import LightGBMError
    if isinstance(ds.data, (str, bytes)):
        from .main import load_text_file
        loaded = load_text_file(str(ds.data), cfg)
        return loaded.X, loaded.label, loaded.weight, [], loaded.group
    if ds.data is None:
        raise LightGBMError(
            "num_machines > 1 needs the raw data to shard rows; pass the "
            "matrix/file to Dataset (free_raw_data has no effect here)")
    if hasattr(ds.data, "tocsr"):
        raise LightGBMError(
            "num_machines > 1 does not accept scipy sparse input yet: "
            "each rank shards dense rows (parallel/multihost.py); pass a "
            "dense matrix or a data file")
    from .basic import _data_to_2d
    X, _names, cat_idx = _data_to_2d(ds.data, ds.feature_name,
                                     categorical_feature)
    y = None if ds.label is None else np.asarray(ds.label, dtype=np.float64)
    w = None if ds.weight is None else np.asarray(ds.weight,
                                                 dtype=np.float64)
    return X, y, w, cat_idx, ds.group


def _serialization_stump(cfg, ds):
    """A serialization-only GBDT populated with just the fields
    save_model_to_string reads (a full init would rebuild a tree learner
    + device score state per rank only to be discarded). Built ONCE per
    training run — the objective init can be O(shard) host work
    (lambdarank's inverse-max-DCG tables) — then reused by every
    snapshot-hook invocation and the final Booster assembly by swapping
    the model list (_serialize_distributed_model)."""
    from .boosting.gbdt import GBDT
    from .objectives import create_objective
    inner = GBDT()
    obj = create_objective(cfg.objective, cfg)
    obj.init(ds.metadata, ds.num_data)
    inner.config = cfg
    inner.objective = obj
    inner.num_class = int(cfg.num_class)
    inner.num_tree_per_iteration = getattr(obj, "num_model_per_iteration", 1)
    inner.max_feature_idx = ds.num_total_features - 1
    inner.feature_names = list(ds.feature_names)
    inner.feature_infos = [GBDT._feature_info(m) for m in ds.bin_mappers]
    inner.monotone_constraints = list(cfg.monotone_constraints)
    return inner


def _serialize_distributed_model(stump, models, num_init_iteration=0):
    """Model text from the (identical-on-every-rank) tree list."""
    stump.models = list(models)
    stump.num_init_iteration = int(num_init_iteration)
    stump.iter = len(stump.models)
    return stump.save_model_to_string()


def _train_distributed(params, train_set, num_boost_round, valid_sets,
                       fobj=None, feval=None, init_model=None,
                       early_stopping_rounds=None, callbacks=None,
                       categorical_feature="auto", learning_rates=None,
                       keep_training_booster=False):
    """num_machines > 1 from the Python API — the reference reaches this
    through params (machines/local_listen_port -> Network::Init inside
    Booster, basic.py set_network / network.cpp); here every participating
    process runs the same program, ranks wire up via jax.distributed, and
    training shards rows over the global mesh (parallel/multihost.py).
    Returns a prediction-ready Booster holding the full model on every
    rank. Custom objectives and callbacks are not supported."""
    from .basic import Booster, params_to_config
    from .parallel.multihost import (init_network, shard_rows,
                                     train_multihost)
    from .utils.log import LightGBMError, Log
    if fobj is not None:
        raise LightGBMError("custom objectives are not supported with "
                            "num_machines > 1")
    if feval is not None:
        raise LightGBMError("custom eval functions are not supported with "
                            "num_machines > 1 (metrics aggregate "
                            "count-weighted across ranks)")
    if callbacks:
        Log.warning("callbacks are ignored with num_machines > 1")
    if learning_rates is not None:
        raise LightGBMError("learning_rates schedules are not supported "
                            "with num_machines > 1; set learning_rate")
    if keep_training_booster:
        Log.warning("keep_training_booster is ignored with "
                    "num_machines > 1 (the returned Booster is "
                    "prediction-ready on every rank)")
    # same params precedence as the single-host path: Dataset-level
    # params (max_bin, binning knobs) overlaid by train() params
    merged = dict(getattr(train_set, "params", None) or {})
    merged.update(params)
    cfg = params_to_config(merged)
    if early_stopping_rounds:
        cfg.early_stopping_round = int(early_stopping_rounds)
    # categorical features: the kwarg wins, else the Dataset's own
    cat = categorical_feature
    if cat == "auto":
        cat = getattr(train_set, "categorical_feature", "auto")
    rank = init_network(cfg)
    X, y, w, cat_idx, grp = _distributed_raw(
        train_set, cfg, "auto" if cat == "auto" else cat)
    if cat not in ("auto", None):
        if any(isinstance(c, str) for c in cat):
            raise LightGBMError("categorical_feature by NAME needs a "
                                "DataFrame; pass column indices with "
                                "num_machines > 1")
        cat_idx = sorted(set(int(c) for c in cat) | set(cat_idx))
    # world=1 is a legal mesh here: the small end of an elastic resume
    # (engine.train routes a matching single-host run into this driver)
    world = max(int(cfg.num_machines), 1)
    if grp is not None:
        # ranking: shard whole queries, never splitting one across ranks
        from .parallel.multihost import shard_queries
        if bool(cfg.pre_partition):
            import numpy as np
            idx, glocal = np.arange(len(X)), np.asarray(grp, np.int64)
        else:
            idx, glocal = shard_queries(grp, rank, world)
    else:
        idx, glocal = shard_rows(len(X), rank, world,
                                 bool(cfg.pre_partition)), None
    Xv = yv = gvalid = None
    if valid_sets:
        others = [v for v in valid_sets if v is not train_set]
        if len(others) < len(valid_sets):
            Log.warning("train-set metrics are not reported with "
                        "num_machines > 1; the train entry of valid_sets "
                        "is ignored")
        if len(others) > 1:
            Log.warning("num_machines > 1 evaluates only the FIRST "
                        "validation set; %d more ignored"
                        % (len(others) - 1))
        vset = others[0] if others else None
        if vset is not None:
            Xv_all, yv_all, _, _, vgrp = _distributed_raw(vset, cfg)
            if yv_all is None:
                raise LightGBMError("the validation Dataset needs a label "
                                    "with num_machines > 1")
            if vgrp is not None:
                from .parallel.multihost import shard_queries
                if bool(cfg.pre_partition):
                    import numpy as np
                    vidx = np.arange(len(Xv_all))
                    gvalid = np.asarray(vgrp, np.int64)
                else:
                    vidx, gvalid = shard_queries(vgrp, rank, world)
            else:
                vidx = shard_rows(len(Xv_all), rank, world,
                                  bool(cfg.pre_partition))
            Xv, yv = Xv_all[vidx], yv_all[vidx]
    # ---- resilience: per-rank auto-resume + snapshot stream ----------
    # checkpoints on the distributed path are model-only (kind=model);
    # resume re-enters the init-model machinery below, so every rank's
    # score shard is reconstructed from the checkpointed model's raw
    # predictions rather than recomputed from scratch
    from .resilience import reshard as resilience_reshard
    from .resilience import restore as resilience_restore
    from .resilience.checkpoint import (CheckpointWriter, array_fingerprint,
                                        config_hash)
    y_local = None if y is None else y[idx]
    # the dataset-GLOBAL fingerprint (pre-shard rows): the identity that
    # survives a mesh resize, unlike the shard-local one below
    global_fp = array_fingerprint(X, y)
    resume_iter = 0
    ck_text = None
    es_resume = None
    ck_orig_init = None
    resume_man = None
    if str(cfg.checkpoint_dir):
        man = resilience_reshard.load_manifest(str(cfg.checkpoint_dir))
        if resilience_reshard.manifest_matches(man, config_hash(cfg),
                                               global_fp):
            # a matching manifest pins this run's binning for EVERY
            # generation: once a run has hopped meshes, even a same-mesh
            # resume must keep the SOURCE bin boundaries — re-deriving
            # them from this mesh's local samples would silently break
            # the bit-exact continuation
            resume_man = man
        if resume_man is not None and int(man.get("world", 1)) != world:
            # this run's snapshots, written by a DIFFERENT mesh size:
            # elastic resume (agreement on iteration + source layout)
            found = resilience_reshard.find_elastic(cfg, rank, world,
                                                    global_fp)
            if found is not None:
                resume_iter, ck_text, ck_meta, _man = found
                es_resume = ck_meta.get("early_stopping")
                ck_orig_init = int(ck_meta.get("n_init", 0))
                from .telemetry import events as telemetry_events
                telemetry_events.count("resilience::reshard_rows",
                                       len(idx), category="resilience")
        else:
            found = resilience_restore.find_distributed(
                cfg, rank, X[idx], y_local, global_fp=global_fp)
            if found is not None:
                resume_iter, ck_text, ck_meta = found
                es_resume = ck_meta.get("early_stopping")
                # iterations of the ORIGINAL init model (if any) embedded
                # in the checkpoint — propagated across resume chains so
                # the round-space <-> tree-list accounting stays right
                ck_orig_init = int(ck_meta.get("n_init", 0))
    model_str = _load_init_model(init_model)
    if ck_text is not None:
        if model_str is not None:
            Log.warning("auto-resume from checkpoint_dir overrides "
                        "init_model")
        model_str = ck_text
        # num_boost_round is the TOTAL target when resuming the same run
        num_boost_round = max(int(num_boost_round) - resume_iter, 0)
    # continued training: seed every rank's score shard with the init
    # model's raw predictions (the distributed analog of
    # _graft_init_model's binned-walk score push), then prepend its trees
    init_stump = None
    isc_local = isc_valid = None
    if model_str is not None:
        init_stump = Booster(model_str=model_str)
        ntpi0 = init_stump._booster.num_tree_per_iteration
        raw = init_stump._booster.predict_raw(X[idx])      # [n, K]
        isc_local = raw[:, 0] if ntpi0 == 1 else raw.T
        if Xv is not None:
            vraw = init_stump._booster.predict_raw(Xv)
            isc_valid = vraw[:, 0] if ntpi0 == 1 else vraw.T
    init_models = (list(init_stump._booster.models)
                   if init_stump is not None else [])
    n_init = init_stump.current_iteration if init_stump is not None else 0
    # round space counts iterations beyond the ORIGINAL init model; on a
    # resume the checkpoint model already contains round-space trees, so
    # the original offset comes from the checkpoint meta, not n_init
    orig_init_iters = ck_orig_init if ck_text is not None else n_init
    stump_cache = {}

    def _stump(ds_):
        if "inner" not in stump_cache:
            stump_cache["inner"] = _serialization_stump(cfg, ds_)
        return stump_cache["inner"]

    snapshot_hook = None
    if str(cfg.checkpoint_dir) and int(cfg.snapshot_freq) > 0:
        writer = CheckpointWriter(
            str(cfg.checkpoint_dir), keep=int(cfg.checkpoint_keep),
            cfg_hash=config_hash(cfg), rank=rank,
            fingerprint=array_fingerprint(X[idx], y_local),
            global_fingerprint=global_fp, world=world)
        assignment = ("pre_partition" if bool(cfg.pre_partition)
                      else "query_blocks" if grp is not None
                      else "round_robin")
        manifest_state = {"written": False}

        def snapshot_hook(it_done, new_trees, ds_, es_state=None):
            # every rank holds the identical trees; each writes its own
            # rank-tagged snapshot (no shared-filesystem assumption); the
            # early-stopping patience clock and the original-init offset
            # ride the snapshot meta
            extra = {"n_init": orig_init_iters}
            if es_state:
                extra["early_stopping"] = es_state
            writer.write_model_text(
                _serialize_distributed_model(
                    _stump(ds_), init_models + list(new_trees),
                    num_init_iteration=n_init),
                it_done, extra_meta=extra)
            # the mesh-layout manifest rides beside the shards (once):
            # world size, row assignment, the global fingerprint, and
            # the global BinMappers — everything a DIFFERENT mesh size
            # needs to resume this run bit-exactly. Written AFTER the
            # first snapshot of this generation: a manifest must never
            # describe a world no snapshot in the directory has yet (a
            # crash in that window would brick the next resume)
            if not manifest_state["written"]:
                resilience_reshard.ensure_manifest(
                    writer.directory,
                    resilience_reshard.build_manifest(
                        config_hash(cfg), global_fp, world, len(X),
                        ds_.bin_mappers, assignment=assignment,
                        group_sizes=grp))
                manifest_state["written"] = True
    result_info = {}
    trees, _mappers, ds, _score = train_multihost(
        cfg, X[idx], y_local,
        num_rounds=int(num_boost_round),
        categorical_features=tuple(cat_idx),
        weight_local=None if w is None else w[idx],
        X_valid=Xv, y_valid=yv,
        group_local=glocal, group_valid=gvalid,
        init_score_local=isc_local, init_score_valid=isc_valid,
        start_iteration=resume_iter, snapshot_hook=snapshot_hook,
        es_resume=es_resume, result_info=result_info,
        mappers_override=(resilience_reshard.manifest_mappers(resume_man)
                          if resume_man is not None else None))
    models_all = init_models + trees
    best_iter = result_info.get("early_stop_best_iter")
    if best_iter is not None:
        # a resumed patience clock rolled back into the restored model:
        # keep the original init model plus best_iter round-space rounds
        keep = ((orig_init_iters + best_iter)
                * int(result_info["trees_per_iteration"]))
        models_all = models_all[:keep]
    return Booster(
        model_str=_serialize_distributed_model(
            _stump(ds), models_all, num_init_iteration=n_init),
        params=dict(params))


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval=True, learning_rates=None,
          keep_training_booster: bool = False, callbacks=None) -> Booster:
    """Train a booster (reference engine.py:18-290)."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = copy.deepcopy(params)
    num_boost_round = _alias_override(params, _ROUND_COUNT_KEYS,
                                      num_boost_round)
    early_stopping_rounds = _alias_override(params, _STOP_ROUND_KEYS,
                                            early_stopping_rounds)
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    from .basic import params_to_config
    from .telemetry import events as telemetry_events
    cfg0 = params_to_config(params)
    # configure before the num_machines split so tpu_telemetry/telemetry_out
    # params also activate the collective spans on the distributed path
    # (multihost scans, allreduce/allgather DCN time)
    telemetry_events.configure_from_config(cfg0)
    # resilience knobs ride the same pattern: the fault plan and the
    # collective retry policy apply to whichever path runs below
    from .resilience import faults as resilience_faults
    from .resilience import retry as resilience_retry
    resilience_faults.configure_from_config(cfg0)
    resilience_retry.configure_from_config(cfg0)
    # crash flight recorder: armed whenever this run can die in a way
    # worth a postmortem (telemetry on / fault plan / multihost); dumps
    # land next to the checkpoints (telemetry/flight.py)
    from .telemetry import flight as telemetry_flight
    telemetry_flight.configure_from_config(cfg0)
    # numerics sentinel: install the tpu_health_abort policy and reset
    # the run-scoped numerics::*/health::* registry state (the flight-
    # ring pattern — an aborted run's split margins must not leak into
    # this run's report or collapse baseline)
    from .telemetry import health as telemetry_health
    telemetry_health.configure_from_config(cfg0)
    # elastic resume onto world=1: a single-host run whose checkpoint_dir
    # holds a MATCHING multi-host run (mesh manifest: same config hash +
    # dataset-global fingerprint, world > 1) continues through the
    # distributed driver — the same sharded grower / stateless-hash
    # bagging the source mesh used, which is what keeps the resumed
    # model bit-exact (resilience/reshard.py)
    elastic_world = None
    if int(cfg0.num_machines) <= 1 and str(cfg0.checkpoint_dir):
        from .resilience import reshard as resilience_reshard
        from .resilience.checkpoint import array_fingerprint, config_hash
        _man = resilience_reshard.load_manifest(str(cfg0.checkpoint_dir))
        if (_man is not None and int(_man.get("world", 1)) > 1
                and resilience_reshard.manifest_matches(
                    _man, config_hash(cfg0))):
            try:
                # fingerprint-only load; _train_distributed re-loads with
                # the caller's categorical coercion (reusing this pass
                # could change cat_idx) — the double load is confined to
                # elastic-resume startup
                _X0, _y0, _w0, _c0, _g0 = _distributed_raw(train_set, cfg0)
                if resilience_reshard.manifest_matches(
                        _man, config_hash(cfg0),
                        array_fingerprint(_X0, _y0)):
                    elastic_world = int(_man["world"])
                else:
                    Log.warning(
                        "checkpoint_dir holds an elastic world=%d run of "
                        "this config but a DIFFERENT dataset; staying on "
                        "the single-host driver" % int(_man["world"]))
            except LightGBMError:
                # raw rows unavailable (freed / sparse input): the
                # distributed driver could not train anyway
                Log.warning("checkpoint_dir holds an elastic manifest but "
                            "the raw rows are unavailable for resharding; "
                            "staying on the single-host driver")
    if int(cfg0.num_machines) > 1 or elastic_world is not None:
        if elastic_world is not None:
            Log.info("Elastic resume: continuing a world=%d run on "
                     "world=1 through the distributed driver"
                     % elastic_world)
        if evals_result is not None:
            # NOTE: no local Log import here — a function-local binding
            # would shadow the module-level Log for the whole function
            Log.warning("evals_result is not populated with "
                        "num_machines > 1")
        try:
            return _train_distributed(
                params, train_set, num_boost_round,
                valid_sets, fobj=fobj, feval=feval,
                init_model=init_model,
                early_stopping_rounds=early_stopping_rounds,
                callbacks=callbacks,
                categorical_feature=categorical_feature,
                learning_rates=learning_rates,
                keep_training_booster=keep_training_booster)
        except LightGBMError as exc:
            # this rank's postmortem; kill / collective-failure sites
            # dump with a sharper reason and mark the exception so a
            # generic re-dump doesn't overwrite it (an EARLIER recovered
            # timeout's dump must not suppress this death's record)
            if not getattr(exc, "_flight_dumped", False):
                telemetry_flight.dump(
                    "train_error:%s" % type(exc).__name__)
            raise
        finally:
            if telemetry_events.enabled():
                from .telemetry.export import maybe_export
                maybe_export()
    if fobj is not None:
        params["objective"] = "none"

    train_set._update_params(params) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)
    plan = _EvalPlan.build(train_set, valid_sets, valid_names)

    registry = _CallbackRegistry(callbacks)
    if verbose_eval is True:
        registry.add(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        registry.add(callback.print_evaluation(verbose_eval))
    es_cb = None
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        es_cb = callback.early_stopping(
            early_stopping_rounds, params.get("first_metric_only", False),
            verbose=bool(verbose_eval))
        registry.add(es_cb)
    if learning_rates is not None:
        registry.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        registry.add(callback.record_evaluation(evals_result))
    from .telemetry.monitor import TrainingMonitor
    monitor = None
    if telemetry_events.enabled():
        # post-iteration CallbackEnv consumer: per-iteration wall time,
        # phase buckets, leaf counts, memory watermarks, recompile counts
        monitor = TrainingMonitor()
        registry.add(monitor)
    saver = None
    if int(cfg0.snapshot_freq) > 0:
        # the reference's snapshot_freq (config.h, alias save_period):
        # here it gates full training-state checkpoints into
        # checkpoint_dir (resilience/), written post-iteration AFTER the
        # early-stopping callback so a stopping round never snapshots
        if str(cfg0.checkpoint_dir):
            from .resilience.checkpoint import (CheckpointWriter,
                                                TrainingSaver, config_hash)
            saver = TrainingSaver(
                CheckpointWriter(str(cfg0.checkpoint_dir),
                                 keep=int(cfg0.checkpoint_keep),
                                 cfg_hash=config_hash(cfg0)),
                int(cfg0.snapshot_freq),
                # the engine-made early-stopping trackers ride the
                # snapshot (user-supplied callbacks stay outside it)
                extra_state_fn=(
                    (lambda: {"early_stopping": es_cb.state_dict()})
                    if es_cb is not None else None))
            registry.add(saver)
        else:
            Log.warning("snapshot_freq=%d has no checkpoint_dir=; set one "
                        "to write resume checkpoints (the CLI train task "
                        "keeps writing model-only snapshots next to "
                        "output_model)" % int(cfg0.snapshot_freq))

    registry.seal()

    booster = Booster(params=params, train_set=train_set)
    model_str = _load_init_model(init_model)
    first_round = 0
    last_round = num_boost_round
    restored = None
    if str(cfg0.checkpoint_dir):
        # auto-resume: newest valid snapshot matching this config +
        # dataset; corruption falls back, a foreign run starts fresh
        from .resilience import restore as resilience_restore
        restored = resilience_restore.find_restorable(cfg0,
                                                      train_set._inner)
    if restored is not None:
        if model_str is not None:
            Log.warning("auto-resume from checkpoint_dir overrides "
                        "init_model")
        first_round = resilience_restore.resume_booster(booster, restored)
        # num_boost_round is the TOTAL target of NEW rounds when resuming
        # the same run: a snapshotted run that itself started from an
        # init model counts its grafted iterations in first_round, so the
        # target is offset by the restored num_init_iteration
        last_round = max(
            num_boost_round + booster._booster.num_init_iteration,
            first_round)
        es_state = resilience_restore.extra_state(restored,
                                                  "early_stopping")
        if es_state and es_cb is not None:
            # the patience clock and rollback point survive the resume
            es_cb.load_state_dict(es_state)
    elif model_str is not None:
        first_round = _graft_init_model(booster, model_str, train_set)
        last_round = first_round + num_boost_round
    plan.attach(booster, params, train_set)
    booster.best_iteration = 0
    # with no per-iteration host work (no before-iter callbacks, no eval
    # sets, no custom objective), the booster may fuse iterations into one
    # jitted multi-tree scan (one device dispatch per K trees)
    inner = getattr(booster, "_booster", None)
    if inner is not None:
        inner.allow_batch = (not registry.has_pre_stage
                             and not plan.active and fobj is None)
        inner.planned_rounds = last_round - first_round
        if saver is not None:
            # fused batches must end exactly on snapshot boundaries
            inner.snapshot_stride = int(cfg0.snapshot_freq)

    def env_for(round_no: int, evals) -> callback.CallbackEnv:
        return callback.CallbackEnv(
            model=booster, params=params, iteration=round_no,
            begin_iteration=first_round, end_iteration=last_round,
            evaluation_result_list=evals)

    final_evals: List = []
    fault_plan = resilience_faults.active()
    try:
        for round_no in range(first_round, last_round):
            if fault_plan is not None:
                # deterministic preemption: raises TrainingKilled before
                # this iteration trains (checkpoints up to here are on
                # disk; check_kill writes its own flight dump)
                fault_plan.check_kill(round_no)
            registry.fire_pre(env_for(round_no, None))
            booster.update(fobj=fobj)
            final_evals = plan.evaluate(booster, feval) if plan.active \
                else []
            try:
                registry.fire_post(env_for(round_no, final_evals))
            except callback.EarlyStopException as stop:
                booster.best_iteration = stop.best_iteration + 1
                final_evals = stop.best_score
                break
    except LightGBMError as exc:
        # a failed run leaves its flight record even when the failure
        # site didn't dump one itself; sites that did (kill, collective
        # exhaustion) mark the exception so their sharper reason wins
        if not getattr(exc, "_flight_dumped", False):
            telemetry_flight.dump("train_error:%s" % type(exc).__name__)
        raise

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for entry in final_evals:
        booster.best_score[entry[0]][entry[1]] = entry[2]
    if monitor is not None:
        booster._telemetry_monitor = monitor
        if inner is not None:
            # flush the async pipeline so the trace's device_wait bucket
            # covers this run's trees (telemetry-on only: the off path
            # keeps the pipeline open exactly as before)
            inner._materialize_pending()
        from .telemetry.export import maybe_export
        maybe_export()   # tpu_telemetry=trace -> Chrome trace + metrics
    return booster


# ---------------------------------------------------------------------------
# cross-validation (reference engine.py:293-610)
# ---------------------------------------------------------------------------

class CVBooster:
    """Ensemble of per-fold boosters (reference _CVBooster, engine.py:296):
    attribute access fans out to every fold and returns the list of
    results."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def fan_out(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return fan_out


def _sklearn_available() -> bool:
    try:
        import sklearn  # noqa: F401
        return True
    except ImportError:
        return False


def _query_memberships(full_data: Dataset) -> np.ndarray:
    """Row -> query id from the dataset's group boundaries (for group-aware
    fold splitting)."""
    sizes = np.asarray(full_data.get_group(), dtype=np.int64)
    return np.repeat(np.arange(len(sizes)), sizes)


def _fold_indices(full_data: Dataset, folds, nfold: int,
                  params: Dict[str, Any], seed: int, stratified: bool,
                  shuffle: bool):
    """Yield (train_idx, test_idx) pairs.

    Explicit `folds` win (an iterable of index pairs or an sklearn-style
    splitter). Otherwise: ranking objectives split whole queries
    (GroupKFold), stratified classification uses StratifiedKFold, and the
    default is an (optionally shuffled) nfold partition of the row range.
    """
    n = full_data.num_data()
    if folds is not None:
        if hasattr(folds, "split"):
            sizes = full_data.get_group()
            groups = (_query_memberships(full_data) if sizes is not None
                      else np.zeros(n, dtype=np.int64))
            return folds.split(X=np.zeros(n), y=full_data.get_label(),
                               groups=groups)
        if not hasattr(folds, "__iter__"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        return folds

    objective = next((params[k] for k in ("objective", "application", "app")
                      if k in params), "")
    if objective in ("lambdarank", "rank_xendcg"):
        if not _sklearn_available():
            raise LightGBMError("scikit-learn is required for ranking cv")
        from sklearn.model_selection import GroupKFold
        return GroupKFold(n_splits=nfold).split(
            X=np.zeros(n), groups=_query_memberships(full_data))
    if stratified:
        if not _sklearn_available():
            raise LightGBMError("scikit-learn is required for stratified cv")
        from sklearn.model_selection import StratifiedKFold
        return StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                               random_state=seed).split(
            X=np.zeros(n), y=full_data.get_label())
    order = (np.random.RandomState(seed).permutation(n) if shuffle
             else np.arange(n))
    held_out = np.array_split(order, nfold)
    return ((np.concatenate(held_out[:k] + held_out[k + 1:]), held_out[k])
            for k in range(nfold))


def _build_fold_boosters(full_data: Dataset, folds, nfold: int,
                         params: Dict[str, Any], seed: int, fpreproc,
                         stratified: bool, shuffle: bool,
                         eval_train_metric: bool) -> CVBooster:
    ensemble = CVBooster()
    for train_idx, test_idx in _fold_indices(full_data, folds, nfold, params,
                                             seed, stratified, shuffle):
        fit_part = full_data.subset(sorted(train_idx))
        held_part = full_data.subset(sorted(test_idx))
        fold_params = params
        if fpreproc is not None:
            fit_part, held_part, fold_params = fpreproc(
                fit_part, held_part, params.copy())
        member = Booster(fold_params, fit_part)
        if eval_train_metric:
            member.add_valid(fit_part, "train")
        member.add_valid(held_part, "valid")
        ensemble.append(member)
    return ensemble


def _pool_fold_evals(per_fold: List[List], eval_train_metric: bool):
    """Mean/std across folds for each (dataset, metric) series
    (reference engine.py:354-372): returns entries shaped like a booster
    eval record plus the cross-fold standard deviation."""
    series = collections.OrderedDict()
    higher_better = {}
    for fold_entries in per_fold:
        for ds_name, metric_name, value, is_higher in fold_entries:
            key = ("%s %s" % (ds_name, metric_name) if eval_train_metric
                   else "valid %s" % metric_name)
            higher_better[key] = is_higher
            series.setdefault(key, []).append(value)
    return [("cv_agg", key, float(np.mean(vals)), higher_better[key],
             float(np.std(vals))) for key, vals in series.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False):
    """Cross-validation (reference engine.py:375-610)."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = copy.deepcopy(params)
    num_boost_round = _alias_override(params, _ROUND_COUNT_KEYS,
                                      num_boost_round)
    early_stopping_rounds = _alias_override(params, _STOP_ROUND_KEYS,
                                            early_stopping_rounds)
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics

    train_set._update_params(params) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)
    if train_set.free_raw_data:
        # cv needs subsetting: keep the raw matrix
        train_set.free_raw_data = False

    # fold indices may come from a one-shot generator: materialize once so
    # the device fast path and the host fold loop see the same folds
    fold_pairs = list(_fold_indices(train_set, folds, nfold, params, seed,
                                    stratified, shuffle))

    registry = _CallbackRegistry(callbacks)
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        registry.add(callback.early_stopping(
            early_stopping_rounds, params.get("first_metric_only", False),
            verbose=False))
    if verbose_eval is True:
        registry.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        registry.add(callback.print_evaluation(verbose_eval, show_stdv))
    registry.seal()

    from .multimodel.cv import maybe_device_cv
    res = maybe_device_cv(params, train_set, num_boost_round, fold_pairs,
                          registry, eval_train_metric, fobj, feval,
                          fpreproc, return_cvbooster)
    if res is not None:
        return res

    ensemble = _build_fold_boosters(train_set, fold_pairs, nfold, params,
                                    seed, fpreproc, stratified, shuffle,
                                    eval_train_metric)

    def env_for(round_no: int, evals) -> callback.CallbackEnv:
        return callback.CallbackEnv(
            model=ensemble, params=params, iteration=round_no,
            begin_iteration=0, end_iteration=num_boost_round,
            evaluation_result_list=evals)

    history = collections.defaultdict(list)
    for round_no in range(num_boost_round):
        registry.fire_pre(env_for(round_no, None))
        per_fold = []
        for member in ensemble.boosters:
            member.update(fobj=fobj)
        for member in ensemble.boosters:
            entries: List = []
            if eval_train_metric:
                entries.extend(member.eval_train(feval))
            entries.extend(member.eval_valid(feval))
            per_fold.append(entries)
        pooled = _pool_fold_evals(per_fold, eval_train_metric)
        for _, key, mean, _, std in pooled:
            history[key + "-mean"].append(mean)
            history[key + "-stdv"].append(std)
        try:
            registry.fire_post(env_for(round_no, pooled))
        except callback.EarlyStopException as stop:
            ensemble.best_iteration = stop.best_iteration + 1
            for key in history:
                history[key] = history[key][:ensemble.best_iteration]
            break
    if return_cvbooster:
        history["cvbooster"] = ensemble
    return dict(history)
