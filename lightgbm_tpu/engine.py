"""Training and cross-validation entry points.

TPU-native rebuild of python-package/lightgbm/engine.py: `train` (:18) with
the same callback orchestration (:198-268) and `cv` (:375) with
stratified/group folds (:299). The per-round work — gradients, tree growth,
score updates — runs as jitted device programs behind Booster.update.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback
from .basic import Booster, Dataset
from .utils.log import LightGBMError, Log

_EARLY_STOP_ALIASES = ("early_stopping_round", "early_stopping_rounds",
                       "early_stopping", "n_iter_no_change")
_NUM_BOOST_ROUND_ALIASES = (
    "num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees",
    "num_round", "num_rounds", "num_boost_round", "n_estimators")


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval=True, learning_rates=None,
          keep_training_booster: bool = False, callbacks=None) -> Booster:
    """Train a booster (reference engine.py:18-290)."""
    params = copy.deepcopy(params)
    # resolve aliases the way the reference does (engine.py:119-155)
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
            Log.warning("Found `%s` in params. Will use it instead of "
                        "argument" % alias)
            break
    for alias in _EARLY_STOP_ALIASES:
        if alias in params:
            early_stopping_rounds = int(params.pop(alias))
            Log.warning("Found `%s` in params. Will use it instead of "
                        "argument" % alias)
            break
    first_metric_only = params.get("first_metric_only", False)

    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    if fobj is not None:
        params["objective"] = "none"

    init_booster_str = None
    init_iteration = 0
    if isinstance(init_model, str):
        with open(init_model) as f:
            init_booster_str = f.read()
    elif isinstance(init_model, Booster):
        init_booster_str = init_model.model_to_string(num_iteration=-1)
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")

    train_set._update_params(params) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)

    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets = []
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Training only accepts Dataset object")
            reduced_valid_sets.append(
                valid_data._update_params(params).set_reference(train_set))
            if valid_names is not None and len(valid_names) > i:
                name_valid_sets.append(valid_names[i])
            else:
                name_valid_sets.append("valid_" + str(i))

    if callbacks is None:
        callbacks = set()
    else:
        for i, cb in enumerate(callbacks):
            cb.__dict__.setdefault("order", i - len(callbacks))
        callbacks = set(callbacks)

    if verbose_eval is True:
        callbacks.add(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        callbacks.add(callback.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.add(callback.early_stopping(
            early_stopping_rounds, first_metric_only,
            verbose=bool(verbose_eval)))
    if learning_rates is not None:
        callbacks.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        callbacks.add(callback.record_evaluation(evals_result))

    callbacks_before_iter = {cb for cb in callbacks
                             if getattr(cb, "before_iteration", False)}
    callbacks_after_iter = callbacks - callbacks_before_iter
    callbacks_before_iter = sorted(callbacks_before_iter,
                                   key=lambda cb: getattr(cb, "order", 0))
    callbacks_after_iter = sorted(callbacks_after_iter,
                                  key=lambda cb: getattr(cb, "order", 0))

    booster = Booster(params=params, train_set=train_set)
    if init_booster_str is not None:
        # continued training: seed scores with the init model's predictions
        init_b = Booster(model_str=init_booster_str)
        init_iteration = init_b.current_iteration
        _seed_scores_from_model(booster, init_b, train_set,
                                reduced_valid_sets)
        booster._booster.models = init_b._booster.models + \
            booster._booster.models
        booster._booster.num_init_iteration = init_iteration
        booster._booster.iter = 0
    if is_valid_contain_train:
        booster.set_train_data_name(train_data_name)
    for valid_set, name_valid_set in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(valid_set, name_valid_set)
    booster.best_iteration = 0
    # with no per-iteration host work (no before-iter callbacks, no eval
    # sets, no custom objective), the booster may fuse iterations into one
    # jitted multi-tree scan (one device dispatch per K trees)
    inner = getattr(booster, "_booster", None)
    if inner is not None:
        inner.allow_batch = (not callbacks_before_iter
                             and valid_sets is None and fobj is None)
        inner.planned_rounds = num_boost_round

    evaluation_result_list: List = []
    for i in range(init_iteration, init_iteration + num_boost_round):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(model=booster, params=params, iteration=i,
                                    begin_iteration=init_iteration,
                                    end_iteration=init_iteration
                                    + num_boost_round,
                                    evaluation_result_list=None))
        booster.update(fobj=fobj)

        evaluation_result_list = []
        if valid_sets is not None:
            if is_valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(model=booster, params=params,
                                        iteration=i,
                                        begin_iteration=init_iteration,
                                        end_iteration=init_iteration
                                        + num_boost_round,
                                        evaluation_result_list=
                                        evaluation_result_list))
        except callback.EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            evaluation_result_list = e.best_score
            break
    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for item in evaluation_result_list:
        dataset_name, eval_name, score = item[0], item[1], item[2]
        booster.best_score[dataset_name][eval_name] = score
    return booster


def _seed_scores_from_model(booster: Booster, init_b: Booster,
                            train_set: Dataset, valid_sets) -> None:
    """Continued training: add the init model's cached predictions to the
    fresh booster's score updaters (reference seeds via _InnerPredictor,
    engine.py:159-165 + boosting handler init)."""
    inner = booster._booster
    ntpi = inner.num_tree_per_iteration
    for i, tree in enumerate(init_b._booster.models):
        # loaded trees carry only real-valued thresholds; bind them to the
        # new dataset's bins before the binned walk
        tree.bind_to_dataset(train_set._inner)
        inner.train_score.add_score_np(
            tree.predict_binned(train_set._inner), i % ntpi)


# ---------------------------------------------------------------------------
# cross-validation (engine.py:293-610)
# ---------------------------------------------------------------------------

class CVBooster:
    """Ensemble of per-fold boosters (reference _CVBooster, engine.py:296)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, fpreproc=None, stratified=False, shuffle=True,
                  eval_train_metric=False):
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group_info = full_data.get_group()
            if group_info is not None:
                group_info = np.asarray(group_info, dtype=np.int64)
                flattened_group = np.repeat(
                    range(len(group_info)), repeats=group_info)
            else:
                flattened_group = np.zeros(num_data, dtype=np.int64)
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label(),
                                groups=flattened_group)
    else:
        if any(params.get(alias, "") in ("lambdarank", "rank_xendcg")
               for alias in ("objective", "application", "app")):
            if not _SKLEARN_INSTALLED():
                raise LightGBMError(
                    "scikit-learn is required for ranking cv")
            from sklearn.model_selection import GroupKFold
            group_info = np.asarray(full_data.get_group(), dtype=np.int64)
            flattened_group = np.repeat(
                range(len(group_info)), repeats=group_info)
            group_kfold = GroupKFold(n_splits=nfold)
            folds = group_kfold.split(X=np.zeros(num_data),
                                      groups=flattened_group)
        elif stratified:
            if not _SKLEARN_INSTALLED():
                raise LightGBMError(
                    "scikit-learn is required for stratified cv")
            from sklearn.model_selection import StratifiedKFold
            skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                                  random_state=seed)
            folds = skf.split(X=np.zeros(num_data), y=full_data.get_label())
        else:
            if shuffle:
                randidx = np.random.RandomState(seed).permutation(num_data)
            else:
                randidx = np.arange(num_data)
            kstep = int(num_data / nfold)
            test_id = [randidx[i:i + kstep] for i in range(0, num_data, kstep)]
            train_id = [np.concatenate([test_id[i] for i in range(nfold)
                                        if k != i]) for k in range(nfold)]
            folds = zip(train_id, test_id)

    ret = CVBooster()
    for train_idx, test_idx in folds:
        train_subset = full_data.subset(sorted(train_idx))
        valid_subset = full_data.subset(sorted(test_idx))
        if fpreproc is not None:
            train_subset, valid_subset, tparam = fpreproc(
                train_subset, valid_subset, params.copy())
        else:
            tparam = params
        cvbooster = Booster(tparam, train_subset)
        if eval_train_metric:
            cvbooster.add_valid(train_subset, "train")
        cvbooster.add_valid(valid_subset, "valid")
        ret.append(cvbooster)
    return ret


def _SKLEARN_INSTALLED() -> bool:
    try:
        import sklearn  # noqa: F401
        return True
    except ImportError:
        return False


def _agg_cv_result(raw_results, eval_train_metric=False):
    """Aggregate per-fold eval results (engine.py:354-372)."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            if eval_train_metric:
                key = "%s %s" % (one_line[0], one_line[1])
            else:
                key = "valid %s" % one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
            for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False):
    """Cross-validation (reference engine.py:375-610)."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = copy.deepcopy(params)
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            Log.warning("Found `%s` in params. Will use it instead of "
                        "argument" % alias)
            num_boost_round = int(params.pop(alias))
            break
    for alias in _EARLY_STOP_ALIASES:
        if alias in params:
            Log.warning("Found `%s` in params. Will use it instead of "
                        "argument" % alias)
            early_stopping_rounds = int(params.pop(alias))
            break
    first_metric_only = params.get("first_metric_only", False)
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics

    train_set._update_params(params) \
             .set_feature_name(feature_name) \
             .set_categorical_feature(categorical_feature)
    if train_set.free_raw_data:
        # cv needs subsetting: keep the raw matrix
        train_set.free_raw_data = False

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds=folds, nfold=nfold,
                            params=params, seed=seed, fpreproc=fpreproc,
                            stratified=stratified, shuffle=shuffle,
                            eval_train_metric=eval_train_metric)

    if callbacks is None:
        callbacks = set()
    else:
        for i, cb in enumerate(callbacks):
            cb.__dict__.setdefault("order", i - len(callbacks))
        callbacks = set(callbacks)
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.add(callback.early_stopping(
            early_stopping_rounds, first_metric_only, verbose=False))
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        callbacks.add(callback.print_evaluation(verbose_eval, show_stdv))

    callbacks_before_iter = {cb for cb in callbacks
                             if getattr(cb, "before_iteration", False)}
    callbacks_after_iter = callbacks - callbacks_before_iter
    callbacks_before_iter = sorted(callbacks_before_iter,
                                   key=lambda cb: getattr(cb, "order", 0))
    callbacks_after_iter = sorted(callbacks_after_iter,
                                  key=lambda cb: getattr(cb, "order", 0))

    for i in range(num_boost_round):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(model=cvfolds, params=params, iteration=i,
                                    begin_iteration=0,
                                    end_iteration=num_boost_round,
                                    evaluation_result_list=None))
        for b in cvfolds.boosters:
            b.update(fobj=fobj)
        raw = []
        for b in cvfolds.boosters:
            one = []
            if eval_train_metric:
                one.extend(b.eval_train(feval))
            one.extend(b.eval_valid(feval))
            raw.append(one)
        res = _agg_cv_result(raw, eval_train_metric)
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(model=cvfolds, params=params,
                                        iteration=i, begin_iteration=0,
                                        end_iteration=num_boost_round,
                                        evaluation_result_list=res))
        except callback.EarlyStopException as e:
            cvfolds.best_iteration = e.best_iteration + 1
            for k in results:
                results[k] = results[k][:cvfolds.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvfolds
    return dict(results)
