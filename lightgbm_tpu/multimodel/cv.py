"""Device-resident cross-validation: folds as lanes of the batched driver.

The host cv loop trains nfold independent boosters, each on a
re-materialized row-subset Dataset. Here the folds instead become lanes
of the multimodel scan program over the ONE full binned Dataset: a
fold's training rows are expressed as a per-fold bag mask (fold mask
AND that fold's own bagging draw), so no fold dataset, no fold device
layout, and no per-fold compiled programs exist. Everything fold-
specific that the host path computes on the host is replicated here
bit-for-bit from the same code or the same RNG recipes:

* per-fold boost-from-average comes from a per-fold objective instance
  initialized on the fold's metadata slice, exactly like the host fold
  booster's;
* per-fold bagging replicates GBDT.bagging/_refresh_bagging_config on
  the fold's n_f rows (same seed, same draw cadence, same zero-count
  fallback) and scatters the mask onto the fold's full-dataset rows;
* column masks and per-tree RNG keys: every host fold booster draws
  identical streams (same config seeds), and so do the lane members;
* metric evaluation is replayed on the host from the materialized
  trees through HostScoreUpdater — the identical walk the host fold
  booster's valid-set updater performs.

Exactness rests on the masked-training identity: with exact f64
histogram accumulation (the CPU lineage's hist_dtype=f64 + use_dp),
out-of-bag rows contribute exact +/-0.0 to every histogram bin and the
in-bag leaf counts drive min_data_in_leaf, so training on the full
layout under a fold mask is bit-identical to training on the fold's
subset layout. `tpu_cv=auto` therefore only engages when the exact-
histogram conditions hold (and falls back silently otherwise);
`tpu_cv=device` forces the path and warns when it cannot; `tpu_cv=off`
always uses host folds.

Known divergence (degenerate regime only, same as multimodel/batch.py):
a host fold booster that hits a no-split tree at round >= 1 rewinds and
keeps redrawing, occasionally re-splitting; the lane freezes at the
first stub.
"""
from __future__ import annotations

import collections
from typing import List, Optional

import numpy as np

from ..utils.log import Log
from . import batch

#: the partitioned grower engages at this row count and re-chunks by
#: num_data, so full-layout-vs-subset program shapes would diverge;
#: the fold fast path stays below it (lightgbm_tpu/treelearner/serial.py)
from ..treelearner.serial import PARTITION_MIN_ROWS


class _FoldBagger:
    """GBDT.bagging / _refresh_bagging_config replicated on a fold's
    n_f rows (boosting/gbdt.py): same seed, same redraw cadence, same
    zero-count fallback — the mask sequence a host fold booster would
    draw, without instantiating one."""

    def __init__(self, cfg, n_f: int):
        self.cfg = cfg
        self.n_f = n_f
        self.rng = np.random.default_rng(cfg.bagging_seed)
        self.bag_data_cnt = n_f
        self.bag_on = bool(cfg.bagging_fraction < 1.0
                           and cfg.bagging_freq > 0)
        if self.bag_on:
            self.bag_data_cnt = max(1, int(cfg.bagging_fraction * n_f))
        self.need_re = self.bag_on
        self._mask = np.ones(n_f, bool)

    def mask(self, it: int) -> np.ndarray:
        cfg = self.cfg
        do_bag = self.bag_data_cnt < self.n_f
        if not ((do_bag and cfg.bagging_freq > 0
                 and it % cfg.bagging_freq == 0) or self.need_re):
            return self._mask
        self.need_re = False
        u = self.rng.random(self.n_f)
        m = u < cfg.bagging_fraction
        self.bag_data_cnt = int(m.sum())
        if self.bag_data_cnt == 0:
            m[self.rng.integers(self.n_f)] = True
            self.bag_data_cnt = 1
        self._mask = m
        return m


def _eval_entries(data_name: str, su, metrics, obj) -> List:
    """Booster._eval_one's record shape, replayed from a host score."""
    score = su.score_host()
    out = []
    for m in metrics:
        vals = m.eval(score, obj)
        for name, v in zip(m.names, vals):
            out.append((data_name, name, v, m.factor_to_bigger_better > 0))
    return out


def _make_metrics(cfg, inner):
    from ..basic import Booster
    ms = Booster._make_metrics(cfg, inner)
    for m in ms:
        m.init(inner.metadata, inner.num_data)
    return ms


def maybe_device_cv(params: dict, train_set, num_boost_round: int,
                    fold_pairs, registry, eval_train_metric: bool,
                    fobj, feval, fpreproc, return_cvbooster: bool
                    ) -> Optional[dict]:
    """Run cv through the batched driver; None means 'use host folds'.

    Called by engine.cv after param normalization and fold-index
    materialization, before the host fold boosters would be built. The
    returned dict is exactly engine.cv's return (history of -mean/-stdv
    series, plus 'cvbooster' when requested).
    """
    from ..basic import params_to_config

    cfg = params_to_config(params)
    mode = str(getattr(cfg, "tpu_cv", "auto")).lower()
    if mode == "off":
        return None

    def bail(reason: str):
        if mode == "device":
            Log.warning("tpu_cv=device: falling back to host cv folds "
                        "(%s)" % reason)
        else:
            Log.debug("device cv unavailable (%s); using host folds"
                      % reason)
        return None

    if fobj is not None or feval is not None or fpreproc is not None:
        return bail("custom objective/metric/preprocessor")
    if getattr(registry, "has_pre_stage", False):
        return bail("before-iteration callbacks")

    from ..basic import Booster
    from ..boosting.gbdt import GBDT
    from ..objectives.base import create_objective

    # driver booster: eligibility gates + the full-dataset learner and
    # objective the compiled programs trace against
    drv = Booster(params, train_set)
    driver_m = batch.Member(drv, params)
    kind, reason = batch.eligibility(driver_m)
    if kind != "scan" or type(driver_m.inner) is not GBDT:
        return bail(reason or "boosting mode")
    obj = driver_m.objective
    if obj.name not in ("regression", "binary"):
        return bail("objective %s" % obj.name)
    inner0 = driver_m.inner
    n = inner0.num_data
    if n >= PARTITION_MIN_ROWS:
        return bail("row count engages the partitioned grower")
    md = inner0.train_data.metadata
    if md is not None and md.init_score is not None:
        return bail("init_score")
    if (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0):
        return bail("balanced bagging")
    if obj.name == "binary" and (
            bool(getattr(cfg, "is_unbalance", False))
            or abs(float(getattr(cfg, "scale_pos_weight", 1.0)) - 1.0)
            > 0):
        return bail("is_unbalance/scale_pos_weight")
    gc = driver_m.learner.grow_config
    if mode != "device" and not (gc.hist_dtype == "f64" and gc.use_dp):
        # masked-full-layout == subset-layout only holds under exact f64
        # histogram accumulation; auto never risks inexact parity
        return bail("histograms are not exact-f64")

    fold_pairs = [(np.sort(np.asarray(tr)).astype(np.int64),
                   np.sort(np.asarray(te)).astype(np.int64))
                  for tr, te in fold_pairs]
    nfold = len(fold_pairs)
    if nfold < 1 or nfold > batch.driver.MM_MAX_BUCKET:
        return bail("nfold outside the batch bucket ladder")

    # per-fold state: subset datasets for metric replay, fold objectives
    # for boost-from-average, fold baggers, lane members
    members: List[batch.Member] = []
    fold_objs = []
    baggers = []
    fold_masks = []
    fit_inners = []
    held_inners = []
    for tr_idx, te_idx in fold_pairs:
        fit_part = train_set.subset(tr_idx)
        held_part = train_set.subset(te_idx)
        fit_part.construct()
        held_part.construct()
        fit_inners.append(fit_part._inner)
        held_inners.append(held_part._inner)
        m = Booster(params, train_set)
        inner = m._booster
        obj_f = create_objective(inner.config.objective, inner.config)
        obj_f.init(fit_part._inner.metadata, fit_part._inner.num_data)
        if not getattr(obj_f, "need_train", True):
            return bail("a fold contains a single class")
        inner.objective = obj_f
        inner.class_need_train = [
            obj_f.class_need_train(k)
            for k in range(inner.num_tree_per_iteration)]
        fold_objs.append(obj_f)
        baggers.append(_FoldBagger(inner.config, len(tr_idx)))
        fm = np.zeros(n, bool)
        fm[tr_idx] = True
        fold_masks.append((fm, tr_idx))
        members.append(batch.Member(m, params))

    Log.debug("device cv: %d folds as one batched program chain" % nfold)
    from ..telemetry import events as telemetry
    telemetry.count("tree_learner::mm_models", float(nfold),
                    category="tree_learner")

    def bag_fn(mi: int, it: int) -> np.ndarray:
        fm, tr_idx = fold_masks[mi]
        sub = baggers[mi].mask(it)
        full = np.zeros(n, bool)
        full[tr_idx] = sub
        return full

    batch.train_scan_group(members, num_boost_round, bag_fn=bag_fn,
                           prog_member=driver_m)

    # ---- host-side eval replay (the host fold loop's per-round evals,
    # walked from the materialized trees) --------------------------------
    from ..boosting.score_updater import HostScoreUpdater
    from .. import engine as _engine
    from .. import callback as _callback

    ensemble = _engine.CVBooster()
    for m in members:
        ensemble.append(m.booster)

    held_sus = [HostScoreUpdater(held_inners[f], 1) for f in range(nfold)]
    fit_sus = ([HostScoreUpdater(fit_inners[f], 1) for f in range(nfold)]
               if eval_train_metric else None)
    held_metrics = [_make_metrics(members[f].inner.config, held_inners[f])
                    for f in range(nfold)]
    fit_metrics = ([_make_metrics(members[f].inner.config, fit_inners[f])
                    for f in range(nfold)] if eval_train_metric else None)
    train_metrics = ([_make_metrics(members[f].inner.config,
                                    fit_inners[f])
                      for f in range(nfold)] if eval_train_metric
                     else None)

    def env_for(round_no: int, evals):
        return _callback.CallbackEnv(
            model=ensemble, params=params, iteration=round_no,
            begin_iteration=0, end_iteration=num_boost_round,
            evaluation_result_list=evals)

    history = collections.defaultdict(list)
    stopped_at = None
    for round_no in range(num_boost_round):
        registry.fire_pre(env_for(round_no, None))
        per_fold = []
        for f in range(nfold):
            models = members[f].inner.models
            if round_no < len(models):
                tree = models[round_no]
                held_sus[f].add_tree(tree, 0)
                if fit_sus is not None:
                    fit_sus[f].add_tree(tree, 0)
            entries: List = []
            if eval_train_metric:
                # the host booster's eval_train reads its device train
                # score; restricted to fold rows it equals this walk
                entries.extend(_eval_entries(
                    "training", fit_sus[f], train_metrics[f],
                    fold_objs[f]))
                entries.extend(_eval_entries(
                    "train", fit_sus[f], fit_metrics[f], fold_objs[f]))
            entries.extend(_eval_entries(
                "valid", held_sus[f], held_metrics[f], fold_objs[f]))
            per_fold.append(entries)
        pooled = _engine._pool_fold_evals(per_fold, eval_train_metric)
        for _, key, mean, _, std in pooled:
            history[key + "-mean"].append(mean)
            history[key + "-stdv"].append(std)
        try:
            registry.fire_post(env_for(round_no, pooled))
        except _callback.EarlyStopException as stop:
            ensemble.best_iteration = stop.best_iteration + 1
            for key in history:
                history[key] = history[key][:ensemble.best_iteration]
            stopped_at = round_no
            break
    if return_cvbooster:
        if stopped_at is not None:
            # host fold boosters stop training at the early-stop round;
            # drop the lanes' extra trees so the ensembles agree
            for m in members:
                inner = m.inner
                if len(inner.models) > stopped_at + 1:
                    del inner.models[stopped_at + 1:]
                    inner.iter = len(inner.models)
        history["cvbooster"] = ensemble
    return dict(history)
