"""Batched (vmapped) fused-iteration drivers for multi-model training.

PR 17 made a whole boosting iteration ONE pure compiled program (the
fused ``lax.scan`` in SerialTreeLearner.train_arrays_scan). That shape —
gradients -> grow -> score update with no host sync — is exactly what
``jax.vmap`` wants: this module wraps the identical per-model scan body
in a model-axis vmap so B boosters train over ONE shared HBM-resident
binned Dataset in a single compiled program.

Batching contract (what is per-model vs shared):

* per-model, traced with a leading ``[B]`` axis: initial scores,
  feature_used carries, per-tree column masks and RNG keys, bag masks,
  shrinkage, SplitParams (lambda_l1/l2, min_gain_to_split,
  min_data_in_leaf, ... ride as traced ``[B]`` scalars), and the
  ``active`` mask below;
* shared (in_axes=None): the DataLayout (ONE HBM copy of the binned
  matrix — see Dataset.to_device's layout cache), FeatureMeta, FixInfo,
  GrowExtras base, the objective's device args, and forced-split info.

Early-stop semantics: a model whose tree fails to split at a global tree
index >= 1 would, in the serial loop, end training there
(GBDT._truncate_if_stopped). In the batch it instead rides an inert
``[B]`` active-mask — its lane keeps dispatching (vmap has no ragged
lanes) but its score/feature_used carries freeze and its emitted trees
are forced to 1-leaf stubs, which the host-side truncation then discards
exactly like the serial stop. One straggler model never blocks the
batch, and the final model texts are bit-identical either way. The
iteration-0 no-split case does NOT deactivate a lane: the reference
keeps the boosted-from-average constant tree and continues.

Program count is independent of B: B is padded up to a power-of-two
bucket (pad lanes replicate model 0 and are discarded), so the compile
surface is the bucket ladder — see analysis/compile_audit.mm_ladder_bound.

Programs are cached on the Dataset (``_mm_scan_cache``) for the same
reason train_arrays_scan caches there: every Booster builds a fresh
learner, and the program only depends on layout + grow config +
objective fingerprint (+ the batch bucket).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import events as telemetry


def _ensure_batching_rules() -> None:
    """jax 0.4.x ships no vmap rule for ``optimization_barrier`` (the
    grower uses it to pin the leaf-value compute order). The barrier is
    semantically the identity, so the rule is exact: bind the batched
    operands and pass the batch dims through — the same rule newer jax
    versions ship built in. Registered once, idempotent."""
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:       # pragma: no cover - jax layout changed
        return
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _rule(batched_args, batch_dims, **params):
        return (optimization_barrier_p.bind(*batched_args, **params),
                batch_dims)

    batching.primitive_batchers[optimization_barrier_p] = _rule


_ensure_batching_rules()

# bucket ladder for the model-batch axis: B pads up to the next power of
# two so distinct sweep widths reuse programs. Sweeps wider than
# MM_MAX_BUCKET train in chunks of MM_MAX_BUCKET (multimodel/batch.py),
# keeping the ladder — and the compile-surface bound — finite.
MM_MIN_BUCKET = 1
MM_MAX_BUCKET = 64


def bucket_for(b: int) -> int:
    """Smallest power-of-two bucket >= b (callers chunk above the cap)."""
    if b < 1:
        raise ValueError("batch size must be >= 1")
    if b > MM_MAX_BUCKET:
        raise ValueError("batch size %d exceeds MM_MAX_BUCKET=%d; chunk "
                         "the sweep first" % (b, MM_MAX_BUCKET))
    return 1 << (b - 1).bit_length()


def _cache(dataset):
    cache = getattr(dataset, "_mm_scan_cache", None)
    if cache is None:
        cache = dataset._mm_scan_cache = {}
    return cache


def get_scan_program(learner, objective, k: int, has_bag: bool):
    """The vmapped k-iteration scan program for ``learner``'s dataset.

    Mirrors SerialTreeLearner.train_arrays_scan's body line for line —
    gradient cast, grower dispatch, f64 leaf-gather score update — so a
    B=1 batch is bit-exact vs the scalar program (pinned in tests), with
    three batch-only additions: the per-iteration bag multiply, the
    active-mask freeze, and the global tree index carried for the
    iteration-0 stub exemption.
    """
    ds = learner.dataset
    cache = _cache(ds)
    key = ("scan", k, bool(has_bag), learner.grow_config,
           objective.static_fingerprint())
    fn = cache.get(key)
    if fn is not None:
        return fn
    telemetry.count("tree_learner::mm_programs", category="tree_learner")

    grad_fn = objective.grad_fn()
    gc = learner.grow_config
    use_part = learner.use_partitioned
    cat, gw = learner.cat_layout, learner.gw_global
    n = ds.num_data
    from ..ops.grow import grow_tree, grow_tree_partitioned

    def one_model(score0, fu0, fmasks, keys, bags, active0, shrink_t,
                  params, layout, base_extras, meta, fix, gargs, forced,
                  idx):
        def body(carry, per):
            score, fu, act = carry
            fmask, kk, bag_i, i = per
            g, h = grad_fn(score, *gargs)
            ex = base_extras._replace(key=kk, feature_used=fu)
            if has_bag:
                # multiply in the gradient's native dtype FIRST (the
                # per-iteration host path's order), then cast: the mask is
                # exact 1.0/0.0 so this is also bit-equal to the serial
                # scan body's cast-then-train on unmasked gradients
                m = bag_i.astype(g.dtype)
                g = (g * m).astype(jnp.float32)
                h = (h * m).astype(jnp.float32)
                bag = bag_i
            else:
                g = g.astype(jnp.float32)
                h = h.astype(jnp.float32)
                bag = jnp.ones(n, bool)
            if use_part:
                arrays, fu2 = grow_tree_partitioned(
                    layout, g, h, bag, meta, params, fmask, fix, gc,
                    gw_global=gw, cat=cat, extras=ex, forced=forced)
            else:
                arrays, fu2 = grow_tree(
                    layout, g, h, bag, meta, params, fmask, fix, gc,
                    cat=cat, extras=ex, forced=forced)
            grew = arrays.num_leaves > 1
            upd = arrays.leaf_value.astype(jnp.float64)[
                arrays.row_leaf] * shrink_t
            score2 = score + jnp.where(act & grew, upd, 0.0)
            # frozen lanes emit 1-leaf stubs (host truncation discards
            # them) and keep their carries; a global-index-0 stub keeps
            # the lane live (reference keeps the constant tree)
            nl = jnp.where(act, arrays.num_leaves, jnp.int32(1))
            act2 = act & (grew | (i == 0))
            fu2 = jnp.where(act, fu2, fu)
            out = arrays._replace(row_leaf=jnp.zeros((0,), jnp.int32),
                                  num_leaves=nl)
            return (score2, fu2, act2), out

        (scoreK, fuK, actK), stacked = jax.lax.scan(
            body, (score0, fu0, active0), (fmasks, keys, bags, idx),
            length=k)
        return scoreK, fuK, actK, stacked

    # B and k are inferred from argument shapes — no static argnums, so
    # this jit contributes exactly one program per (bucket, k) shape and
    # the compile surface is the analytic ladder bound
    @jax.jit
    def run(layout, score0s, fu0s, fmasks, keys, bags, active0, shrinks,
            base_extras, meta, params, fix, gargs, forced, idx):
        vm = jax.vmap(
            one_model,
            in_axes=(0, 0, 0, 0, 0, 0, 0, 0,
                     None, None, None, None, None, None, None))
        return vm(score0s, fu0s, fmasks, keys, bags, active0, shrinks,
                  params, layout, base_extras, meta, fix, gargs, forced,
                  idx)

    cache[key] = run
    return run


def get_grad_program(learner, objective):
    """Vmapped gradient program: [B, N] scores -> ([B, N] g, [B, N] h) in
    the objective's native dtype (GOSS samples on the host from these)."""
    ds = learner.dataset
    cache = _cache(ds)
    key = ("grad", objective.static_fingerprint())
    fn = cache.get(key)
    if fn is not None:
        return fn
    telemetry.count("tree_learner::mm_programs", category="tree_learner")
    grad_fn = objective.grad_fn()

    @jax.jit
    def run(scores, gargs):
        return jax.vmap(lambda s: grad_fn(s, *gargs))(scores)

    cache[key] = run
    return run


def get_step_program(learner, objective, has_weight: bool):
    """Vmapped single-tree step from EXTERNAL gradients: the GOSS path.

    Serial GOSS never fuses iterations (its sampling needs |g*h| on the
    host each round), so its batched twin is a per-iteration program
    taking host-orchestrated per-model gradients, sample weights and bag
    masks. Mirrors GBDT._train_one_iter_fast's tree step exactly: the
    weight multiply happens in the gradient's native dtype and the
    grower performs the f32 cast internally.
    """
    ds = learner.dataset
    cache = _cache(ds)
    key = ("step", bool(has_weight), learner.grow_config,
           objective.static_fingerprint())
    fn = cache.get(key)
    if fn is not None:
        return fn
    telemetry.count("tree_learner::mm_programs", category="tree_learner")

    gc = learner.grow_config
    use_part = learner.use_partitioned
    cat, gw = learner.cat_layout, learner.gw_global
    from ..ops.grow import grow_tree, grow_tree_partitioned

    def one_model(score, g, h, w, bag, fmask, kk, fu, act, shrink_t,
                   params, layout, base_extras, meta, fix, forced, i):
        if has_weight:
            g2 = g * w
            h2 = h * w
        else:
            m = bag.astype(g.dtype)
            g2 = g * m
            h2 = h * m
        ex = base_extras._replace(key=kk, feature_used=fu)
        if use_part:
            arrays, fu2 = grow_tree_partitioned(
                layout, g2, h2, bag, meta, params, fmask, fix, gc,
                gw_global=gw, cat=cat, extras=ex, forced=forced)
        else:
            arrays, fu2 = grow_tree(
                layout, g2, h2, bag, meta, params, fmask, fix, gc,
                cat=cat, extras=ex, forced=forced)
        grew = arrays.num_leaves > 1
        upd = arrays.leaf_value.astype(jnp.float64)[
            arrays.row_leaf] * shrink_t
        score2 = score + jnp.where(act & grew, upd, 0.0)
        nl = jnp.where(act, arrays.num_leaves, jnp.int32(1))
        act2 = act & (grew | (i == 0))
        fu2 = jnp.where(act, fu2, fu)
        out = arrays._replace(row_leaf=jnp.zeros((0,), jnp.int32),
                              num_leaves=nl)
        return score2, fu2, act2, out

    @jax.jit
    def run(layout, scores, gs, hs, ws, bags, fmasks, keys, fus, active,
            shrinks, base_extras, meta, params, fix, forced, i):
        vm = jax.vmap(
            one_model,
            in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                     None, None, None, None, None, None))
        return vm(scores, gs, hs, ws, bags, fmasks, keys, fus, active,
                  shrinks, params, layout, base_extras, meta, fix,
                  forced, i)

    cache[key] = run
    return run


def pad_lanes(b: int, bucket: int, tree):
    """Pad every [b, ...] leaf of ``tree`` to [bucket, ...] by replicating
    lane 0 (pad lanes train model 0 again; outputs are discarded)."""
    if b == bucket:
        return tree

    def pad(x):
        reps = jnp.repeat(x[:1], bucket - b, axis=0)
        return jnp.concatenate([x, reps], axis=0)

    return jax.tree.map(pad, tree)


def stack_members(values):
    """Stack a per-member list of pytrees along a new leading model axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *values)


def np_stack_members(values):
    return np.stack(values)
