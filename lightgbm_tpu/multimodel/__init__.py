"""Multi-model training: B boosters, ONE shared binned Dataset, one
compiled program per program-shape (never per model).

Public surface:

* ``sweep(params_grid, train_set, num_boost_round)`` — train one
  Booster per grid point. Models sharing compile-time attributes train
  batched through a model-axis ``vmap`` of the fused-iteration scan
  (multimodel/driver.py); per-model knobs (learning_rate, lambda_l1/l2,
  min_gain_to_split, min_data_in_leaf, seeds, bagging) ride as traced
  ``[B]`` inputs. Model texts are bit-exact vs the serial outer loop.
* ``maybe_device_cv(...)`` (multimodel/cv.py) — engine.cv's
  device-resident fast path: folds become lanes of the same batched
  driver, sharing the full binned Dataset via per-fold bag masks
  instead of re-materialized fold datasets.

See multimodel/batch.py for the orchestration and the exactness
argument; driver.py for the compiled-program shapes and the bucket
ladder that keeps the compile surface independent of B.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Union

from .batch import Member, train_members
from .driver import MM_MAX_BUCKET, MM_MIN_BUCKET, bucket_for

__all__ = ["sweep", "expand_grid", "MM_MAX_BUCKET", "MM_MIN_BUCKET",
           "bucket_for"]


def expand_grid(params_grid: Union[Dict, Sequence[Dict]]) -> List[Dict]:
    """A sequence of param dicts passes through; a single dict expands
    list-valued entries into their cartesian product (insertion order),
    scalars broadcasting to every combination."""
    if isinstance(params_grid, dict):
        keys = [k for k, v in params_grid.items()
                if isinstance(v, (list, tuple))]
        fixed = {k: v for k, v in params_grid.items()
                 if not isinstance(v, (list, tuple))}
        if not keys:
            return [dict(params_grid)]
        out = []
        for combo in itertools.product(
                *[params_grid[k] for k in keys]):
            p = dict(fixed)
            p.update(dict(zip(keys, combo)))
            out.append(p)
        return out
    return [dict(p) for p in params_grid]


def sweep(params_grid: Union[Dict, Sequence[Dict]], train_set,
          num_boost_round: int = 100) -> List:
    """Train one Booster per grid point over one shared Dataset.

    Returns the Boosters in grid order. Each is a fully independent,
    ordinary Booster (own objective/config/model text); only the tree
    growth was dispatched batched. Grid points whose configuration
    cannot batch (DART/RF, CEGB, custom learners, ...) train through
    their own serial loop transparently.
    """
    from ..basic import Booster
    grid = expand_grid(params_grid)
    if not grid:
        raise ValueError("empty params grid")
    members = []
    for p in grid:
        bst = Booster(dict(p), train_set)
        bst.best_iteration = 0
        members.append(Member(bst, dict(p)))
    train_members(members, num_boost_round)
    return [m.booster for m in members]
