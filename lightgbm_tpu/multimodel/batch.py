"""Host-side orchestration for batched multi-model training.

A *member* is an ordinary Booster — its own config, objective instance,
tree learner, column sampler, bagging RNG and score cache — whose tree
GROWTH is dispatched through the shared vmapped programs in
multimodel/driver.py instead of per-model programs. Everything the
serial path computes on the host (bagging masks, column masks, per-tree
RNG keys, boost-from-average, tree materialization, stop truncation) is
computed by the member's OWN booster code here, in the same order the
serial loop would call it, so the per-model inputs fed to the batched
program are bit-identical to what the member would have fed its own
program — that, plus the vmapped body mirroring the scalar scan body,
is the whole bit-exactness argument.

Members are partitioned into *static groups*: models that share every
compile-time attribute (grower config, objective fingerprint, bagging
on/off, boosting kind). Each group trains through one program chain;
per-model knobs that differ inside a group (learning_rate, lambdas,
min_gain_to_split, min_data_in_leaf, seeds, ...) ride as traced [B]
inputs. Members that cannot take the batched path at all (DART/RF,
custom learners, CEGB, persist-eligible setups, unsupported objectives)
fall back to their own serial training loop — the sweep still returns
one Booster per grid point either way.

Known divergence (documented, degenerate regime only): after a model's
first no-split tree at round >= 1 the serial loop rewinds and keeps
drawing — occasionally re-splitting before a later truncation — while
the batched active-mask freezes the lane at the first stub. Both paths
truncate at the first stub, so they differ only when a serial re-split
lands AFTER a stub, i.e. when training has already effectively stopped.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import events as telemetry
from ..utils.log import Log
from . import driver

# mirrors GBDT._batch_size: one fused 16-iteration program plus a k=1
# tail program, and the guard that keeps a single batch under the
# remote worker's watchdog at very large row*feature products
MM_BATCH_K = 16
MM_SIZE_GUARD = 150_000_000


class Member:
    """One sweep entry: the public Booster plus its training internals."""

    def __init__(self, booster, params: dict):
        self.booster = booster
        self.params = params
        self.inner = booster._booster
        self.learner = self.inner.tree_learner
        self.objective = self.inner.objective


def eligibility(member: Member) -> Tuple[Optional[str], str]:
    """(kind, reason): kind is "scan" (gbdt), "goss", or None with the
    fallback reason. Mirrors the gates GBDT._batch_size applies before
    fusing, minus bagging (precomputed masks make bagged members
    batchable here) and plus the CEGB/forced-split extras the shared
    GrowExtras base cannot carry per-model."""
    from ..boosting.gbdt import GBDT
    from ..boosting.goss import GOSS
    from ..treelearner.serial import SerialTreeLearner
    inner = member.inner
    if type(inner) is GOSS:
        kind = "goss"
    elif type(inner) is GBDT:
        kind = "scan"
    else:
        return None, "boosting type %s" % type(inner).__name__
    obj = member.objective
    if obj is None:
        return None, "custom objective"
    if not obj.supports_fused_scan:
        return None, "objective lacks device gradients"
    if obj.is_renew_tree_output:
        return None, "objective renews leaves on host"
    if inner.num_tree_per_iteration != 1:
        return None, "multiclass"
    if not all(inner.class_need_train):
        return None, "untrainable class"
    if inner.train_data.num_features <= 0:
        return None, "no features"
    learner = member.learner
    if type(learner) is not SerialTreeLearner:
        return None, "non-serial tree learner"
    gc = learner.grow_config
    if gc.use_cegb or gc.use_cegb_lazy:
        return None, "CEGB"
    if gc.n_forced != 0:
        return None, "forced splits"
    if learner.can_persist_scan(obj):
        # the persist driver is a different program family; batching it
        # is future work — fall back so results match the serial path
        return None, "persist-scan eligible"
    return kind, ""


def _has_bag(inner) -> bool:
    return bool(inner.bag_data_cnt < inner.num_data
                or inner.balanced_bagging)


def group_key(member: Member, kind: str):
    """Compile-time identity: members sharing a key share programs."""
    return (kind, _has_bag(member.inner) if kind == "scan" else True,
            member.learner.grow_config,
            member.objective.static_fingerprint())


def serial_train(member: Member, num_boost_round: int) -> None:
    """The member's own serial loop, flags set exactly as engine.train
    sets them (no callbacks / eval sets / custom objective here)."""
    inner = member.inner
    inner.allow_batch = True
    inner.planned_rounds = num_boost_round
    for _ in range(num_boost_round):
        inner.train_one_iter(None, None)
    inner._materialize_pending()


def _stack_params(members: List[Member]):
    return jax.tree.map(lambda *xs: jnp.stack(xs),
                        *[m.learner.params for m in members])


def _member_slice(stacked, b: int, keep_axis: bool = False):
    if keep_axis:
        return jax.tree.map(lambda a: a[b:b + 1], stacked)
    return jax.tree.map(lambda a: a[b], stacked)


def train_scan_group(members: List[Member], num_boost_round: int,
                     bag_fn=None, prog_member: Optional[Member] = None
                     ) -> None:
    """Batched training for a gbdt static group: fused 16-iteration
    blocks (k=1 tail), per-model bag masks precomputed by each member's
    own bagging() in serial call order.

    ``bag_fn(member_index, iteration) -> bool [N] mask`` overrides the
    members' own bagging (the cv fast path injects fold-intersect-bag
    masks); ``prog_member`` supplies the learner/objective the compiled
    programs and traced gradient args come from when the members' own
    objectives are not full-dataset-shaped (cv's per-fold objectives)."""
    b = len(members)
    bucket = driver.bucket_for(b)
    lead = prog_member if prog_member is not None else members[0]
    learner0 = lead.learner
    n = lead.inner.num_data
    has_bag = bag_fn is not None or _has_bag(members[0].inner)
    fn16 = None
    fn1 = None

    # per-member carries; initialized from each member's own state
    init0s = [m.inner.boost_from_average(0, True) for m in members]
    scores = [m.inner.train_score.score_device(0) for m in members]
    fus = [m.learner._feature_used_dev
           if m.learner._feature_used_dev is not None
           else m.learner._extras_base.feature_used for m in members]
    act = jnp.ones((b,), bool)
    shrinks = jnp.asarray([m.inner.shrinkage_rate for m in members],
                          jnp.float64)
    paramss = _stack_params(members)
    base = learner0._extras_base
    gargs = lead.objective._grad_args()

    score_c = jnp.stack(scores)
    fu_c = jnp.stack(fus)

    size_guarded = (n * max(lead.inner.train_data.num_features, 1)
                    > MM_SIZE_GUARD)
    pos = 0
    while pos < num_boost_round:
        remaining = num_boost_round - pos
        k = (MM_BATCH_K if remaining >= MM_BATCH_K and not size_guarded
             else 1)
        fmasks = []
        keys = []
        bags = []
        for mi, m in enumerate(members):
            fmasks.append(np.stack([m.learner.col_sampler.sample()
                                    for _ in range(k)]))
            keys.append(np.stack(
                [np.asarray(m.learner._next_extras().key)
                 for _ in range(k)]))
            if bag_fn is not None:
                bags.append(np.stack([bag_fn(mi, it)
                                      for it in range(pos, pos + k)]))
            elif has_bag:
                bm = []
                for it in range(pos, pos + k):
                    m.inner.bagging(it)
                    bm.append(np.asarray(m.inner._bag_mask_dev))
                bags.append(np.stack(bm))
        fmasks = jnp.asarray(np.stack(fmasks))
        keys = jnp.asarray(np.stack(keys))
        bags = (jnp.asarray(np.stack(bags)) if has_bag
                else jnp.zeros((b, k, 0), bool))
        idx = jnp.arange(pos, pos + k, dtype=jnp.int32)

        fn = fn16 if k == MM_BATCH_K else fn1
        if fn is None:
            fn = driver.get_scan_program(learner0, lead.objective, k,
                                         has_bag)
            if k == MM_BATCH_K:
                fn16 = fn
            else:
                fn1 = fn

        args = driver.pad_lanes(
            b, bucket,
            (score_c, fu_c, fmasks, keys, bags, act, shrinks, paramss))
        score_p, fu_p, fmasks_p, keys_p, bags_p, act_p, shr_p, par_p = args
        scoreK, fuK, actK, stacked = fn(
            learner0.layout, score_p, fu_p, fmasks_p, keys_p, bags_p,
            act_p, shr_p, base, learner0.meta, par_p, learner0.fix,
            gargs, learner0.forced, idx)
        score_c, fu_c, act = scoreK[:b], fuK[:b], actK[:b]
        for i, m in enumerate(members):
            inner = m.inner
            stacked_b = _member_slice(stacked, i)
            # boost_from_average is a no-op past iteration 0: only the
            # first block's entry carries the init-score bias
            init0 = init0s[i] if pos == 0 else 0.0
            inner._pending_batches.append(
                (len(inner.models), stacked_b, inner.shrinkage_rate,
                 (init0,), "gbdt"))
            inner.models.extend([None] * k)
            inner.iter += k
        pos += k

    for i, m in enumerate(members):
        m.inner.train_score._score[0] = score_c[i]
        m.learner._feature_used_dev = fu_c[i]
        m.inner._materialize_pending()


def train_goss_group(members: List[Member], num_boost_round: int) -> None:
    """Batched training for a GOSS static group: per-iteration programs
    (GOSS's gradient-dependent sampling runs on the host between the
    batched gradient and grow steps, driven by each member's own
    GOSS.bagging so the sampling RNG stream is bit-identical)."""
    b = len(members)
    bucket = driver.bucket_for(b)
    lead = members[0]
    learner0 = lead.learner
    n = lead.inner.num_data

    grad_fn = driver.get_grad_program(learner0, lead.objective)
    step_fn = driver.get_step_program(learner0, lead.objective,
                                      has_weight=True)

    init0s = [m.inner.boost_from_average(0, True) for m in members]
    score_c = jnp.stack([m.inner.train_score.score_device(0)
                         for m in members])
    fu_c = jnp.stack([m.learner._feature_used_dev
                      if m.learner._feature_used_dev is not None
                      else m.learner._extras_base.feature_used
                      for m in members])
    act = jnp.ones((b,), bool)
    shrinks = jnp.asarray([m.inner.shrinkage_rate for m in members],
                          jnp.float64)
    paramss = _stack_params(members)
    base = learner0._extras_base
    gargs = lead.objective._grad_args()
    ones_w = np.ones(n, np.float32)

    for it in range(num_boost_round):
        score_p = driver.pad_lanes(b, bucket, score_c)
        g_all, h_all = grad_fn(score_p, gargs)
        ws, bags, fmasks, keys = [], [], [], []
        for i, m in enumerate(members):
            inner = m.inner
            # the member's own GOSS sampler sees exactly the gradients
            # its serial twin would (class axis restored)
            inner._cur_grad_hess = (g_all[i:i + 1], h_all[i:i + 1])
            inner.bagging(it)
            w = inner._bag_weight_dev
            ws.append(np.asarray(w) if w is not None else ones_w)
            bags.append(np.asarray(inner._bag_mask_dev))
            fmasks.append(np.asarray(m.learner.col_sampler.sample()))
            keys.append(np.asarray(m.learner._next_extras().key))
        args = driver.pad_lanes(
            b, bucket,
            (score_c, g_all[:b], h_all[:b],
             jnp.asarray(np.stack(ws)), jnp.asarray(np.stack(bags)),
             jnp.asarray(np.stack(fmasks)), jnp.asarray(np.stack(keys)),
             fu_c, act, shrinks, paramss))
        (score_p, g_p, h_p, w_p, bag_p, fm_p, key_p, fu_p, act_p,
         shr_p, par_p) = args
        score2, fu2, act2, stacked = step_fn(
            learner0.layout, score_p, g_p, h_p, w_p, bag_p, fm_p, key_p,
            fu_p, act_p, shr_p, base, learner0.meta, par_p,
            learner0.fix, learner0.forced,
            jnp.asarray(it, jnp.int32))
        score_c, fu_c, act = score2[:b], fu2[:b], act2[:b]
        for i, m in enumerate(members):
            inner = m.inner
            stacked_b = _member_slice(stacked, i, keep_axis=True)
            init0 = init0s[i] if it == 0 else 0.0
            inner._pending_batches.append(
                (len(inner.models), stacked_b, inner.shrinkage_rate,
                 (init0,), "gbdt"))
            inner.models.extend([None])
            inner.iter += 1

    for i, m in enumerate(members):
        m.inner.train_score._score[0] = score_c[i]
        m.learner._feature_used_dev = fu_c[i]
        m.inner._materialize_pending()


def train_members(members: List[Member], num_boost_round: int) -> None:
    """Partition into static groups, chunk to the bucket cap, train."""
    groups: dict = {}
    fallback: List[Member] = []
    for m in members:
        kind, reason = eligibility(m)
        if kind is None:
            Log.debug("multimodel: %s falls back to serial (%s)"
                      % (type(m.inner).__name__, reason))
            fallback.append(m)
            continue
        groups.setdefault(group_key(m, kind), []).append(m)
    for key, ms in groups.items():
        kind = key[0]
        trainer = (train_goss_group if kind == "goss"
                   else train_scan_group)
        for lo in range(0, len(ms), driver.MM_MAX_BUCKET):
            chunk = ms[lo:lo + driver.MM_MAX_BUCKET]
            telemetry.count("tree_learner::mm_models", float(len(chunk)),
                            category="tree_learner")
            trainer(chunk, num_boost_round)
    for m in fallback:
        serial_train(m, num_boost_round)
