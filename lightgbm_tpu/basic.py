"""Dataset and Booster: the user-facing core API.

TPU-native rebuild of python-package/lightgbm/basic.py. The reference binds
a C library via ctypes (basic.py:24, _load_lib); here Dataset wraps the
host-side BinnedDataset (data/dataset.py) whose binned matrix ships to TPU
HBM at Booster construction, and Booster drives the jitted boosting engine
(boosting/) directly — same surface, no C round-trips. Lazy construction
(_lazy_init, reference basic.py:868), reference-aligned validation binning
(set_reference / Dataset alignment, basic.py:730-1090), pandas and
categorical handling (basic.py:331-418) all follow the reference semantics.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .config import Config, params_to_config, _METRIC_ALIASES
from .data.dataset import BinnedDataset
from .metrics import create_metric
from .objectives import create_objective
from .utils.log import LightGBMError, Log

try:
    import pandas as pd
    _PANDAS = True
except ImportError:  # pragma: no cover
    _PANDAS = False

try:
    from scipy import sparse as _sp
    _SCIPY = True
except ImportError:  # pragma: no cover
    _SCIPY = False


def _data_to_2d(data, feature_name="auto", categorical_feature="auto"):
    """Coerce input data to (float64 2D array, feature_names, cat_indices).

    Mirrors the pandas/categorical handling in reference basic.py:331-418
    (_data_from_pandas): category dtypes are codified, bad object columns
    rejected.
    """
    cat_idx: List[int] = []
    names: Optional[List[str]] = None
    if _PANDAS and isinstance(data, pd.DataFrame):
        names = [str(c) for c in data.columns]
        df = data.copy()
        auto_cat = categorical_feature == "auto"
        cat_names = ([] if auto_cat or categorical_feature is None
                     else list(categorical_feature))
        for i, col in enumerate(df.columns):
            if str(df[col].dtype) == "category":
                df[col] = df[col].cat.codes.astype(np.float64).replace(-1, np.nan) \
                    if hasattr(df[col].cat.codes, "replace") \
                    else df[col].cat.codes.astype(np.float64)
                if auto_cat:
                    cat_idx.append(i)
            if (not auto_cat) and (col in cat_names or i in cat_names):
                cat_idx.append(i)
        bad = [c for c in df.columns
               if df[c].dtype == object]
        if bad:
            raise LightGBMError(
                "DataFrame.dtypes for data must be int, float or bool. Did "
                "not expect the data types in the following fields: "
                + ", ".join(str(b) for b in bad))
        X = df.values.astype(np.float64)
    elif _SCIPY and _sp.issparse(data):
        X = np.asarray(data.todense(), dtype=np.float64)
    elif isinstance(data, list):
        X = np.asarray(data, dtype=np.float64)
    else:
        X = np.asarray(data, dtype=np.float64)
    if X.ndim == 1:
        X = X.reshape(-1, 1)
    if categorical_feature not in ("auto", None) and not cat_idx:
        for c in categorical_feature:
            if isinstance(c, int):
                cat_idx.append(c)
            elif names is not None and c in names:
                cat_idx.append(names.index(c))
    if feature_name not in ("auto", None):
        names = list(feature_name)
    return X, names, sorted(set(cat_idx))


def _label_from_pandas(label):
    if _PANDAS and isinstance(label, (pd.Series, pd.DataFrame)):
        return np.asarray(label).reshape(-1)
    return label


class Dataset:
    """Training/validation data container (reference basic.py:730)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, silent=False):
        self.data = data
        self.label = _label_from_pandas(label)
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._inner: Optional[BinnedDataset] = None
        self.used_indices = None
        self._predictor = None

    # -- laziness (reference _lazy_init, basic.py:868) -------------------
    def construct(self) -> "Dataset":
        if self._inner is not None:
            return self
        if self.data is None:
            raise LightGBMError(
                "Cannot construct Dataset since the raw data has been freed; "
                "set free_raw_data=False when creating the Dataset")
        if isinstance(self.data, (str, bytes)):
            return self._construct_from_path(str(self.data))
        cfg = params_to_config(self.params)
        ref_inner = None
        if self.reference is not None:
            self.reference.construct()
            ref_inner = self.reference._inner
        if _SCIPY and _sp.issparse(self.data):
            # streaming CSR ingest: never densifies the full matrix
            # (dense-on-device is a TPU design choice; dense-on-host at
            # ingest would need ~n*features*8 bytes)
            cat_idx = (list(self.categorical_feature)
                       if isinstance(self.categorical_feature, (list, tuple))
                       else ())
            self._inner = BinnedDataset.from_sparse(
                self.data, cfg,
                categorical_features=cat_idx,
                label=self.label,
                weight=self.weight,
                group=self.group,
                init_score=self.init_score,
                feature_names=(list(self.feature_name)
                               if isinstance(self.feature_name, (list, tuple))
                               else None),
                reference=ref_inner,
            )
            self._raw_X = None if self.free_raw_data else self.data
            if self.free_raw_data:
                self.data = None
            return self
        X, names, cat_idx = _data_to_2d(self.data, self.feature_name,
                                        self.categorical_feature)
        self._inner = BinnedDataset.from_matrix(
            X, cfg,
            categorical_features=cat_idx,
            label=self.label,
            weight=self.weight,
            group=self.group,
            init_score=self.init_score,
            feature_names=names,
            reference=ref_inner,
        )
        self._raw_X = None if self.free_raw_data else X
        if self.free_raw_data:
            self.data = None
        return self

    def _construct_from_path(self, path: str) -> "Dataset":
        """File-path Dataset (reference Dataset('file') via
        LGBM_DatasetCreateFromFile): binary cache fast path
        (dataset_loader.cpp:179-274), two_round streaming, or one-round
        text load; save_binary writes <path>.bin for next time."""
        from .data.loader import load_text_file
        cfg = params_to_config(self.params)

        if not BinnedDataset.is_binary_file(path) \
                and BinnedDataset.is_binary_file(path + ".bin"):
            # CheckCanLoadFromBin probes <data>.bin (dataset_loader.cpp:179)
            path = path + ".bin"
        if BinnedDataset.is_binary_file(path) and self.reference is not None:
            # a binary cache is only usable for a reference-aligned set when
            # its binning layout matches the reference's exactly (e.g. it
            # was saved FROM a reference-aligned validation set)
            self.reference.construct()
            cached = BinnedDataset.from_binary(path)
            if cached.layout_matches(self.reference._inner):
                self._inner = cached
                self._apply_field_overrides()
                self.data = None if self.free_raw_data else self.data
                return self
            if path != str(self.data):
                # auto-probed <data>.bin next to a text file: re-bin the text
                Log.warning("Ignoring binary cache %s: its bin layout does "
                            "not match the reference dataset" % path)
                path = str(self.data)
            else:
                raise LightGBMError(
                    "Binary dataset %s was binned standalone and does not "
                    "match the reference's bin layout; recreate it from the "
                    "raw text/matrix" % path)
        if BinnedDataset.is_binary_file(path):
            self._inner = BinnedDataset.from_binary(path)
            self._apply_field_overrides()
            self.data = None if self.free_raw_data else self.data
            return self
        cat_idx = (list(self.categorical_feature)
                   if isinstance(self.categorical_feature, (list, tuple))
                   else ())
        ref_inner = None
        if self.reference is not None:
            self.reference.construct()
            ref_inner = self.reference._inner
        if cfg.two_round and ref_inner is None:
            self._inner = BinnedDataset.from_text_two_round(
                path, cfg, categorical_features=cat_idx)
            self._apply_field_overrides()
        else:
            loaded = load_text_file(path, cfg)
            self._inner = BinnedDataset.from_matrix(
                loaded.X, cfg, categorical_features=cat_idx,
                label=(self.label if self.label is not None
                       else loaded.label),
                weight=self.weight if self.weight is not None
                else loaded.weight,
                group=self.group if self.group is not None else loaded.group,
                init_score=(self.init_score if self.init_score is not None
                            else loaded.init_score),
                feature_names=loaded.feature_names,
                reference=ref_inner)
        if cfg.save_binary and not path.endswith(".bin"):
            self._inner.save_binary(path + ".bin")
        self.data = None if self.free_raw_data else self.data
        return self

    def _apply_field_overrides(self) -> None:
        """User-supplied fields take precedence over whatever the loaded
        dataset (binary cache / parsed file) carried."""
        md = self._inner.metadata
        if self.label is not None:
            md.set_label(self.label)
        if self.weight is not None:
            md.set_weight(self.weight)
        if self.group is not None:
            md.set_query(self.group)
        if self.init_score is not None:
            md.set_init_score(self.init_score)

    @property
    def constructed(self) -> bool:
        return self._inner is not None

    # -- field access (reference set_field/get_field) --------------------
    def set_label(self, label) -> "Dataset":
        self.label = _label_from_pandas(label)
        if self._inner is not None:
            self._inner.metadata.set_label(self.label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_query(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_init_score(init_score)
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self._inner is not None and self.reference is not reference:
            raise LightGBMError("Cannot set reference after constructed")
        self.reference = reference
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name not in (None, "auto"):
            self.feature_name = feature_name
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if categorical_feature not in (None, "auto"):
            if self._inner is not None:
                Log.warning("categorical_feature set after construction is "
                            "ignored")
            else:
                self.categorical_feature = categorical_feature
        return self

    def get_label(self):
        if self._inner is not None:
            return self._inner.metadata.label
        return self.label

    def get_weight(self):
        if self._inner is not None:
            return self._inner.metadata.weight
        return self.weight

    def get_group(self):
        if self._inner is not None and \
                self._inner.metadata.query_boundaries is not None:
            return np.diff(self._inner.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        if self._inner is not None:
            return self._inner.metadata.init_score
        return self.init_score

    def get_field(self, field_name):
        return {"label": self.get_label, "weight": self.get_weight,
                "group": self.get_group,
                "init_score": self.get_init_score}[field_name]()

    def set_field(self, field_name, data):
        return {"label": self.set_label, "weight": self.set_weight,
                "group": self.set_group,
                "init_score": self.set_init_score}[field_name](data)

    # -- info ------------------------------------------------------------
    def num_data(self) -> int:
        self.construct()
        return self._inner.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._inner.num_total_features

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing this dataset's BinMappers (reference
        Dataset.subset, basic.py:1330)."""
        self.construct()
        X = self._raw_X if getattr(self, "_raw_X", None) is not None else None
        if X is None:
            raise LightGBMError("subset requires free_raw_data=False")
        idx = np.asarray(used_indices)
        n = self.num_data()
        # recompute per-fold query sizes from the parent group vector so
        # ranking cv folds keep their query structure
        group_sub = None
        parent_group = self.get_group()
        if parent_group is not None and len(parent_group):
            qid = np.repeat(np.arange(len(parent_group)),
                            np.asarray(parent_group, dtype=np.int64))
            qid_sub = qid[idx]
            if len(qid_sub):
                change = np.flatnonzero(np.diff(qid_sub) != 0)
                bounds = np.concatenate([[0], change + 1, [len(qid_sub)]])
                group_sub = np.diff(bounds)
        # slice init_score rows ([n], [n*k] class-major, or [n, k])
        init_sub = None
        isc = self.get_init_score()
        if isc is not None:
            isc = np.asarray(isc)
            if isc.ndim == 2:
                init_sub = isc[idx]
            elif isc.size == n:
                init_sub = isc[idx]
            elif isc.size % n == 0:
                init_sub = isc.reshape(-1, n)[:, idx].reshape(-1)
            else:
                raise LightGBMError(
                    "init_score size %d is not compatible with num_data %d"
                    % (isc.size, n))
        sub = Dataset(X[idx],
                      label=None if self.label is None else
                      np.asarray(self.label)[idx],
                      reference=self,
                      weight=None if self.weight is None else
                      np.asarray(self.weight)[idx],
                      group=group_sub,
                      init_score=init_sub,
                      params=params or self.params,
                      free_raw_data=self.free_raw_data)
        sub.used_indices = idx
        return sub

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append other's features to this Dataset (reference
        Dataset.add_features_from / LGBM_DatasetAddFeaturesFrom)."""
        self.construct()
        other.construct()
        self._inner.add_features_from(other._inner)
        if getattr(self, "_raw_X", None) is not None \
                and getattr(other, "_raw_X", None) is not None:
            self._raw_X = np.concatenate([self._raw_X, other._raw_X], axis=1)
        else:
            self._raw_X = None
        return self

    def _update_params(self, params) -> "Dataset":
        if params:
            self.params.update(params)
        return self

    def _reverse_update_params(self) -> "Dataset":
        return self

    def _set_predictor(self, predictor) -> "Dataset":
        self._predictor = predictor
        return self


class Booster:
    """The trained model handle (reference basic.py:1704)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent=False):
        from .boosting import create_boosting
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict = {}
        self.train_set = None
        self._train_data_name = "training"
        self._valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance, "
                                "met %s" % type(train_set).__name__)
            cfg = params_to_config(self.params)
            train_set._update_params(self.params)
            train_set.construct()
            self.train_set = train_set
            self._cfg = cfg
            inner = train_set._inner
            objective = create_objective(cfg.objective, cfg)
            if objective is not None:
                objective.init(inner.metadata, inner.num_data)
            self._booster = create_boosting(cfg.boosting)
            self._booster.init(cfg, inner, objective)
            self._metrics = self._make_metrics(cfg, inner)
            for m in self._metrics:
                m.init(inner.metadata, inner.num_data)
        elif model_file is not None:
            with open(model_file) as f:
                model_str = f.read()
            self._init_from_string(model_str)
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file or model string to create Booster instance")

    def _init_from_string(self, model_str: str) -> None:
        from .boosting import create_boosting
        self._cfg = params_to_config(self.params)
        self._booster = create_boosting("gbdt")
        self._booster.config = self._cfg
        self._booster.load_model_from_string(model_str)
        self._metrics = []

    @staticmethod
    def _make_metrics(cfg: Config, inner: BinnedDataset):
        """Config metric list; falls back to the objective's own metric
        (reference config.cpp metric default resolution)."""
        names = list(cfg.metric)
        if not names:
            default = _METRIC_ALIASES.get(cfg.objective)
            if default and default != "none":
                names = [default]
        out = []
        for n in names:
            if n in ("none",):
                continue
            m = create_metric(n, cfg)
            if m is not None:
                out.append(m)
        return out

    # ------------------------------------------------------------------
    def reset_parameter(self, params: dict) -> "Booster":
        """Change training-control parameters of the Booster (reference
        Booster.reset_parameter, python-package basic.py /
        LGBM_BoosterResetParameter): routes through GBDT.reset_config,
        which warns on structurally-fixed keys."""
        if params:
            self._booster.reset_config(params)
            self.params.update(params)
        return self

    # ------------------------------------------------------------------
    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing model's leaf values to new data
        (reference Booster.refit, basic.py:2614 / GBDT::RefitTree): tree
        structures are kept; each leaf output is re-estimated from the new
        data's gradients and blended by decay_rate."""
        import copy
        self._booster._materialize_pending()
        if not self._booster.models:
            raise LightGBMError("Cannot refit an empty model")
        X, _, _ = _data_to_2d(data)
        params = dict(self.params)
        params.pop("input_model", None)
        new_set = Dataset(X, label, params=params)
        new_booster = Booster(params=params, train_set=new_set)
        self._booster._materialize_pending()
        new_booster._booster.models = [copy.deepcopy(t)
                                       for t in self._booster.models]
        new_booster._booster.refit(np.ascontiguousarray(X, np.float64),
                                   decay_rate=float(decay_rate))
        return new_booster

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if not isinstance(data, Dataset):
            raise TypeError("Validation data should be Dataset instance, "
                            "met %s" % type(data).__name__)
        if data is not self.train_set:
            # the training set itself may ride as a named valid set (cv's
            # eval_train_metric folds); it is its own reference
            data.set_reference(self.train_set)
        data.construct()
        self._valid_sets.append(data)
        self.name_valid_sets.append(name)
        cfg = self._cfg
        metrics = self._make_metrics(cfg, data._inner)
        self._booster.add_valid_dataset(data._inner, metrics, name)
        return self

    # ------------------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting round (reference basic.py:2089). Returns True when
        no further splits were possible (training finished)."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Replacing train_set is not yet supported "
                                "on device_type=tpu")
        if fobj is None:
            return self._booster.train_one_iter(None, None)
        if self._cfg.boosting == "rf":
            raise LightGBMError("RF mode does not support custom objective")
        preds = self._booster.train_score.score_host()
        grad, hess = fobj(preds, self.train_set)
        return self.__boost(grad, hess)

    def __boost(self, grad, hess) -> bool:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        hess = np.ascontiguousarray(hess, dtype=np.float32)
        ntpi = self._booster.num_tree_per_iteration
        n = self._booster.num_data
        if grad.size != n * ntpi:
            raise ValueError(
                "Lengths of gradients (%d) and expected (%d) don't match"
                % (grad.size, n * ntpi))
        return self._booster.train_one_iter(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._booster.rollback_one_iter()
        return self

    @property
    def current_iteration(self):
        return self._booster.current_iteration

    def num_trees(self) -> int:
        return len(self._booster.models)

    def num_model_per_iteration(self) -> int:
        return self._booster.num_tree_per_iteration

    def num_feature(self) -> int:
        return self._booster.max_feature_idx + 1

    # ------------------------------------------------------------------
    def _eval_one(self, score: np.ndarray, metrics, data_name: str,
                  feval=None, dataset: Optional[Dataset] = None):
        out = []
        obj = self._booster.objective
        for m in metrics:
            vals = m.eval(score, obj)
            for name, v in zip(m.names, vals):
                out.append((data_name, name, v,
                            m.factor_to_bigger_better > 0))
        if feval is not None:
            ntpi = self._booster.num_tree_per_iteration
            n = score.size // ntpi
            preds = score if ntpi == 1 else score
            res = feval(preds, dataset)
            if isinstance(res, tuple):
                res = [res]
            for name, v, is_higher_better in res:
                out.append((data_name, name, v, is_higher_better))
        return out

    def eval_train(self, feval=None):
        score = self._booster.train_score.score_host()
        return self._eval_one(score, self._metrics, self._train_data_name,
                              feval, self.train_set)

    def eval_valid(self, feval=None):
        out = []
        for i, (su, metrics) in enumerate(zip(self._booster.valid_score,
                                              self._booster.valid_metrics)):
            out.extend(self._eval_one(su.score_host(), metrics,
                                      self.name_valid_sets[i], feval,
                                      self._valid_sets[i]
                                      if i < len(self._valid_sets) else None))
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        if data is self.train_set:
            return self.eval_train(feval)
        for i, vs in enumerate(self._valid_sets):
            if data is vs:
                su = self._booster.valid_score[i]
                return self._eval_one(su.score_host(),
                                      self._booster.valid_metrics[i], name,
                                      feval, data)
        raise LightGBMError("Data for eval must be train or valid set")

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, data_has_header: bool = False,
                is_reshape: bool = True, start_iteration: int = 0, **kwargs):
        if _SCIPY and _sp.issparse(data):
            # stream CSR row blocks through the dense predictor instead of
            # densifying the whole matrix (reference PredictForCSR,
            # src/c_api.cpp, walks rows sparsely); each block densifies to
            # ~32MB so predict memory stays bounded regardless of n
            csr = data.tocsr()
            step = max(1, (32 << 20) // max(int(csr.shape[1]) * 8, 1))
            if csr.shape[0] > step:
                outs = [self.predict(
                    np.asarray(csr[i:i + step].todense(), dtype=np.float64),
                    num_iteration=num_iteration, raw_score=raw_score,
                    pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                    data_has_header=data_has_header, is_reshape=is_reshape,
                    start_iteration=start_iteration, **kwargs)
                    for i in range(0, int(csr.shape[0]), step)]
                return np.concatenate(outs, axis=0)
        X, _, _ = _data_to_2d(data)
        # reference LGBM_BoosterPredict* shape guard (predict_disable_
        # shape_check): feature-count mismatch is fatal unless disabled
        nf_model = self._booster.max_feature_idx + 1
        if X.shape[1] != nf_model and not bool(kwargs.get(
                "predict_disable_shape_check",
                self.params.get("predict_disable_shape_check", False))):
            raise LightGBMError(
                "The number of features in data (%d) is not the same as "
                "it was in training data (%d).\nYou can set "
                "predict_disable_shape_check=true to discard this error, "
                "but please be aware what you are doing." % (X.shape[1],
                                                             nf_model))
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        # inference device selection (predict/ subsystem): kwarg wins over
        # the Booster params; default cpu keeps the numpy walk
        device = str(kwargs.get(
            "predict_device",
            self.params.get("predict_device", "cpu"))).lower()
        if pred_leaf:
            return self._booster.predict_leaf_index(
                X, start_iteration, num_iteration, device=device)
        if pred_contrib:
            if device == "tpu":
                # native TreeSHAP stays host-side (logged, counter-pinned)
                from .telemetry import events as _ev
                _ev.count("predict::fallback_pred_contrib", 1,
                          category="predict")
                Log.info("predict_device=tpu does not cover pred_contrib; "
                         "using the host TreeSHAP path")
            return self._booster.predict_contrib(
                X, start_iteration, num_iteration)
        early_stop = None
        # the reference only honors pred_early_stop where accuracy is not
        # required (binary/multiclass objectives, NeedAccuratePrediction)
        obj = getattr(self._booster, "objective", None)
        es_ok = obj is not None and getattr(obj, "name", "") in (
            "binary", "multiclass", "multiclassova")
        if es_ok and kwargs.get(
                "pred_early_stop", self.params.get("pred_early_stop",
                                                   False)):
            early_stop = (
                int(kwargs.get("pred_early_stop_freq",
                               self.params.get("pred_early_stop_freq", 10))),
                float(kwargs.get("pred_early_stop_margin",
                                 self.params.get("pred_early_stop_margin",
                                                 10.0))))
        if early_stop is not None and device == "tpu":
            # the margin early exit is a host-walk optimization; honoring
            # it beats ignoring it silently
            from .telemetry import events as _ev
            _ev.count("predict::fallback_early_stop", 1, category="predict")
            Log.info("pred_early_stop is host-only; predict_device=tpu "
                     "request served by the host predictor")
            device = "cpu"
        return self._booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=num_iteration,
                                     early_stop=early_stop, device=device)

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        return self._booster.save_model_to_string(start_iteration,
                                                  num_iteration)

    def save_model(self, filename: str,
                   num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        with open(filename, "w") as f:
            f.write(self.model_to_string(num_iteration, start_iteration))
        return self

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> dict:
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        return self._booster.dump_model(start_iteration, num_iteration)

    def model_from_string(self, model_str: str, verbose=True) -> "Booster":
        self._init_from_string(model_str)
        return self

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        imp = self._booster.feature_importance(
            importance_type, iteration if iteration else 0)
        if importance_type == "split":
            return imp.astype(np.int32)
        return imp

    def feature_name(self) -> List[str]:
        return list(self._booster.feature_names)

    # -- pickling -------------------------------------------------------
    def __getstate__(self):
        state = {"params": self.params,
                 "model_str": self.model_to_string(num_iteration=-1),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self.train_set = None
        self._train_data_name = "training"
        self._valid_sets = []
        self.name_valid_sets = []
        self._init_from_string(state["model_str"])

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, _):
        model_str = self.model_to_string(num_iteration=-1)
        return Booster(model_str=model_str)
