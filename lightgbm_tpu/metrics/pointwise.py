"""Pointwise metrics: regression, binary, cross-entropy families.

TPU-native rebuild of src/metric/regression_metric.hpp,
binary_metric.hpp and xentropy_metric.hpp: each LossOnPoint becomes a
vectorized numpy expression over the full score vector; the weighted
average and the per-metric AverageLoss overrides (rmse sqrt,
gamma_deviance ×2) follow the reference. When an objective is supplied,
scores go through its ConvertOutput first (regression_metric.hpp:74-92)
— except for the binary/xentropy families, which apply their own sigmoid
with the objective's sigmoid parameter (binary_metric.hpp:57-76).
"""
from __future__ import annotations

import numpy as np

from ..utils.log import Log
from .base import K_EPSILON, Metric, register


class _PointwiseMetric(Metric):
    """Common Eval loop (regression_metric.hpp:58-95)."""

    metric_name = ""
    check_label = None         # optional callable
    convert_via_objective = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.check_label is not None:
            if not bool(self.check_label(self.label)):
                Log.fatal("Metric %s with invalid label" % self.metric_name)

    @property
    def names(self):
        return [self.metric_name]

    def loss(self, label, score):
        raise NotImplementedError

    def average(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score, objective):
        if objective is not None and self.convert_via_objective:
            score = objective.convert_output(score)
        pt = self.loss(self.label.astype(np.float64), score)
        if self.weight is not None:
            sum_loss = float(np.sum(pt * self.weight))
        else:
            sum_loss = float(np.sum(pt))
        return [self.average(sum_loss, self.sum_weights)]


@register
class L2Metric(_PointwiseMetric):
    metric_name = "l2"

    def loss(self, label, score):
        d = score - label
        return d * d


@register
class RMSEMetric(L2Metric):
    metric_name = "rmse"

    def average(self, sum_loss, sum_weights):
        return float(np.sqrt(sum_loss / sum_weights))


@register
class L1Metric(_PointwiseMetric):
    metric_name = "l1"

    def loss(self, label, score):
        return np.fabs(score - label)


@register
class QuantileMetric(_PointwiseMetric):
    metric_name = "quantile"

    def loss(self, label, score):
        delta = label - score
        a = self.config.alpha
        return np.where(delta < 0, (a - 1.0) * delta, a * delta)


@register
class HuberLossMetric(_PointwiseMetric):
    metric_name = "huber"

    def loss(self, label, score):
        diff = score - label
        a = self.config.alpha
        return np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))


@register
class FairLossMetric(_PointwiseMetric):
    metric_name = "fair"

    def loss(self, label, score):
        x = np.fabs(score - label)
        c = self.config.fair_c
        return c * x - c * c * np.log(1.0 + x / c)


@register
class PoissonMetric(_PointwiseMetric):
    metric_name = "poisson"

    def loss(self, label, score):
        score = np.maximum(score, 1e-10)
        return score - label * np.log(score)


@register
class MAPEMetric(_PointwiseMetric):
    metric_name = "mape"

    def loss(self, label, score):
        return np.fabs(label - score) / np.maximum(1.0, np.fabs(label))


@register
class GammaMetric(_PointwiseMetric):
    metric_name = "gamma"
    check_label = staticmethod(lambda y: np.all(y > 0))

    def loss(self, label, score):
        # regression_metric.hpp:261-272 (psi = 1)
        theta = -1.0 / score
        b = -np.log(np.maximum(-theta, 1e-300))
        c = np.log(np.maximum(label, 1e-300)) - np.log(np.maximum(label, 1e-300))
        return -((label * theta - b) + c)


@register
class GammaDevianceMetric(_PointwiseMetric):
    metric_name = "gamma_deviance"
    check_label = staticmethod(lambda y: np.all(y > 0))

    def loss(self, label, score):
        tmp = label / (score + 1e-9)
        return tmp - np.log(np.maximum(tmp, 1e-300)) - 1.0

    def average(self, sum_loss, sum_weights):
        return sum_loss * 2.0


@register
class TweedieMetric(_PointwiseMetric):
    metric_name = "tweedie"

    def loss(self, label, score):
        rho = self.config.tweedie_variance_power
        score = np.maximum(score, 1e-10)
        a = label * np.exp((1 - rho) * np.log(score)) / (1 - rho)
        b = np.exp((2 - rho) * np.log(score)) / (2 - rho)
        return -a + b


# ---------------------------------------------------------------------------
# binary family (binary_metric.hpp): score -> prob via objective sigmoid
# ---------------------------------------------------------------------------

def _xent_loss(label, prob):
    """XentLoss (xentropy_metric.hpp:35-44): full CE for soft labels."""
    eps = K_EPSILON
    p1 = np.where(1.0 - prob > eps, -np.log(np.maximum(1.0 - prob, eps)),
                  -np.log(eps))
    p2 = np.where(prob > eps, -np.log(np.maximum(prob, eps)), -np.log(eps))
    return (1.0 - label) * p1 + label * p2


class _BinaryMetric(_PointwiseMetric):
    """binary_metric.hpp:24-98: prob = ConvertOutput(score) when objective
    given, else score is already a probability."""

    def eval(self, score, objective):
        prob = objective.convert_output(score) if objective is not None else score
        pt = self.loss(self.label.astype(np.float64), prob)
        if self.weight is not None:
            sum_loss = float(np.sum(pt * self.weight))
        else:
            sum_loss = float(np.sum(pt))
        return [self.average(sum_loss, self.sum_weights)]


@register
class BinaryLoglossMetric(_BinaryMetric):
    metric_name = "binary_logloss"

    def loss(self, label, prob):
        # binary_metric.hpp:117-130 (hard 0/1 by label sign)
        pos = label > 0
        neg_l = np.where(1.0 - prob > K_EPSILON,
                         -np.log(np.maximum(1.0 - prob, K_EPSILON)),
                         -np.log(K_EPSILON))
        pos_l = np.where(prob > K_EPSILON,
                         -np.log(np.maximum(prob, K_EPSILON)),
                         -np.log(K_EPSILON))
        return np.where(pos, pos_l, neg_l)


@register
class BinaryErrorMetric(_BinaryMetric):
    metric_name = "binary_error"

    def loss(self, label, prob):
        return np.where(prob <= 0.5, (label > 0).astype(np.float64),
                        (label <= 0).astype(np.float64))


@register
class AUCMetric(Metric):
    """AUC via the reference's threshold-walk accumulation
    (binary_metric.hpp:159-253), vectorized: group equal scores, pairs of
    (neg in group) x (pos below + half of group's pos)."""

    metric_name = "auc"

    @property
    def names(self):
        return ["auc"]

    @property
    def factor_to_bigger_better(self):
        return 1.0

    def eval(self, score, objective):
        order = np.argsort(-score, kind="stable")
        s = score[order]
        lab = self.label[order]
        w = self.weight[order] if self.weight is not None else np.ones_like(lab)
        pos = np.where(lab > 0, w, 0.0).astype(np.float64)
        neg = np.where(lab <= 0, w, 0.0).astype(np.float64)
        # group by equal score (descending): boundaries where score changes
        new_grp = np.empty(len(s), dtype=bool)
        if len(s) == 0:
            return [1.0]
        new_grp[0] = True
        new_grp[1:] = s[1:] != s[:-1]
        gid = np.cumsum(new_grp) - 1
        ngrp = gid[-1] + 1
        grp_pos = np.bincount(gid, weights=pos, minlength=ngrp)
        grp_neg = np.bincount(gid, weights=neg, minlength=ngrp)
        sum_pos_before = np.concatenate([[0.0], np.cumsum(grp_pos)[:-1]])
        accum = float(np.sum(grp_neg * (grp_pos * 0.5 + sum_pos_before)))
        sum_pos = float(np.sum(pos))
        sum_weights = float(np.sum(w))
        if sum_pos > 0.0 and sum_pos != sum_weights:
            return [accum / (sum_pos * (sum_weights - sum_pos))]
        return [1.0]


# ---------------------------------------------------------------------------
# xentropy family (xentropy_metric.hpp)
# ---------------------------------------------------------------------------

@register
class CrossEntropyMetric(_BinaryMetric):
    """xentropy_metric.hpp:71-160: soft-label CE; sigmoid applied when an
    objective is attached (NOTE in reference: raw score must be prob else)."""

    metric_name = "cross_entropy"

    def loss(self, label, prob):
        return _xent_loss(label, prob)


@register
class CrossEntropyLambdaMetric(Metric):
    """xentropy_metric.hpp:166-243: CE in the lambda parameterization;
    hhat = log1p(exp(score)) when objective given, else score is hhat."""

    metric_name = "cross_entropy_lambda"

    @property
    def names(self):
        return ["cross_entropy_lambda"]

    def eval(self, score, objective):
        if objective is not None:
            hhat = np.log1p(np.exp(score))
        else:
            hhat = score
        w = self.weight if self.weight is not None else 1.0
        prob = 1.0 - np.exp(-w * hhat)
        pt = _xent_loss(self.label.astype(np.float64), prob)
        # note: reference weights only through the lambda link, the sum is
        # unweighted (xentropy_metric.hpp:196-222 divides by num_data)
        return [float(np.sum(pt)) / self.num_data]


@register
class KLDivMetric(Metric):
    """xentropy_metric.hpp:249-330: KL divergence = CE - entropy(label)."""

    metric_name = "kldiv"

    @property
    def names(self):
        return ["kldiv"]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label.astype(np.float64)
        # YentLoss: entropy of the label itself (xentropy_metric.hpp:60-68)
        ent = np.zeros_like(lab)
        m = (lab > 0) & (lab < 1)
        ent[m] = lab[m] * np.log(lab[m]) + (1 - lab[m]) * np.log(1 - lab[m])
        if self.weight is not None:
            self._sum_ent = float(np.sum(ent * self.weight))
        else:
            self._sum_ent = float(np.sum(ent))

    def eval(self, score, objective):
        prob = (objective.convert_output(score) if objective is not None
                else score)
        pt = _xent_loss(self.label.astype(np.float64), prob)
        if self.weight is not None:
            s = float(np.sum(pt * self.weight))
        else:
            s = float(np.sum(pt))
        return [(s + self._sum_ent) / self.sum_weights]
