"""Multiclass metrics: multi_error, multi_logloss, auc_mu.

TPU-native rebuild of src/metric/multiclass_metric.hpp. The per-row rec
buffer + ConvertOutput loop (:37-109) becomes a [N, K] matrix op; auc_mu
(:183-294) keeps the reference's pairwise-hyperplane algorithm with its
exact tie handling.
"""
from __future__ import annotations

import numpy as np

from ..utils.log import Log
from .base import K_EPSILON, Metric, register


class _MulticlassMetric(Metric):
    metric_name = ""

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)

    @property
    def names(self):
        return [self.metric_name]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self.label.astype(np.int64)
        if li.min() < 0 or li.max() >= self.num_class:
            Log.fatal("Label must be in [0, %d) for metric %s"
                      % (self.num_class, self.metric_name))
        self._label_int = li

    def _scores_nk(self, score, objective):
        """flat class-major [K*N] -> per-row [N, K], converted."""
        nk = score.reshape(self.num_class, self.num_data).T  # [N, K]
        if objective is not None:
            nk = objective.convert_output(nk)
        return nk

    def loss(self, label_int, probs_nk):
        raise NotImplementedError

    def eval(self, score, objective):
        nk = self._scores_nk(score, objective)
        pt = self.loss(self._label_int, nk)
        if self.weight is not None:
            s = float(np.sum(pt * self.weight))
        else:
            s = float(np.sum(pt))
        return [s / self.sum_weights]


@register
class MultiErrorMetric(_MulticlassMetric):
    metric_name = "multi_error"

    @property
    def names(self):
        k = self.config.multi_error_top_k
        return ["multi_error" if k == 1 else "multi_error@%d" % k]

    def loss(self, label_int, probs_nk):
        # multiclass_metric.hpp:123-132: error unless #(score >= score[label])
        # stays within top_k
        true_score = probs_nk[np.arange(len(label_int)), label_int]
        num_larger = np.sum(probs_nk >= true_score[:, None], axis=1)
        return (num_larger > self.config.multi_error_top_k).astype(np.float64)


@register
class MultiSoftmaxLoglossMetric(_MulticlassMetric):
    metric_name = "multi_logloss"

    def loss(self, label_int, probs_nk):
        p = probs_nk[np.arange(len(label_int)), label_int]
        return -np.log(np.maximum(p, K_EPSILON))


@register
class AucMuMetric(Metric):
    """AUC-mu (multiclass_metric.hpp:183-294; Kleiman & Page, ICML'19)."""

    metric_name = "auc_mu"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        w = list(config.auc_mu_weights)
        K = self.num_class
        if w:
            if len(w) != K * K:
                Log.fatal("auc_mu_weights must have %d elements" % (K * K))
            self.class_weights = np.asarray(w, dtype=np.float64).reshape(K, K)
        else:
            # default: 1 everywhere except 0 diagonal (config.cpp:310-325)
            self.class_weights = 1.0 - np.eye(K)

    @property
    def names(self):
        return ["auc_mu"]

    @property
    def factor_to_bigger_better(self):
        return 1.0

    def eval(self, score, objective):
        K = self.num_class
        N = self.num_data
        lab = self.label.astype(np.int64)
        scores_kn = score.reshape(K, N)
        S = np.zeros((K, K))
        class_sizes = np.bincount(lab, minlength=K)
        for i in range(K):
            for j in range(i + 1, K):
                curr_v = self.class_weights[i] - self.class_weights[j]
                t1 = curr_v[i] - curr_v[j]
                sel = (lab == i) | (lab == j)
                idx = np.nonzero(sel)[0]
                v_a = curr_v @ scores_kn[:, idx]
                dist = t1 * v_a
                lab_sel = lab[idx]
                # sort ascending by dist; ties put class j first
                # (multiclass_metric.hpp:248-258)
                order = np.lexsort((-lab_sel, dist))
                d_sorted = dist[order]
                l_sorted = lab_sel[order]
                num_j = 0.0
                last_j_dist = 0.0
                num_current_j = 0.0
                s_ij = 0.0
                for k in range(len(order)):
                    if l_sorted[k] == i:
                        if abs(d_sorted[k] - last_j_dist) < K_EPSILON:
                            s_ij += num_j - 0.5 * num_current_j
                        else:
                            s_ij += num_j
                    else:
                        num_j += 1
                        if abs(d_sorted[k] - last_j_dist) < K_EPSILON:
                            num_current_j += 1
                        else:
                            last_j_dist = d_sorted[k]
                            num_current_j = 1
                S[i, j] = s_ij
        ans = 0.0
        for i in range(K):
            for j in range(i + 1, K):
                denom = class_sizes[i] * class_sizes[j]
                if denom > 0:
                    ans += S[i, j] / denom
        return [2.0 * ans / (K * (K - 1))]
