"""Metric interface + factory.

TPU-native rebuild of the reference metric layer (include/LightGBM/metric.h,
factory src/metric/metric.cpp:16-60). Metrics evaluate host-side over numpy
score arrays (scores are pulled from device once per eval round); the sorted
metrics (AUC, NDCG, MAP) match the reference's stable-sort tie semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..utils.log import Log

K_EPSILON = 1e-15


class Metric:
    """Base metric (metric.h). `eval(score, objective)` returns a list of
    floats aligned with `names`; score is the raw model score, flat
    class-major [num_class * num_data] for multiclass (reference layout)."""

    def __init__(self, config):
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.sum_weights = 0.0

    @property
    def names(self) -> List[str]:
        raise NotImplementedError

    @property
    def factor_to_bigger_better(self) -> float:
        """-1 for losses (smaller is better), +1 for scores."""
        return -1.0

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        if self.weight is None:
            self.sum_weights = float(num_data)
        else:
            self.sum_weights = float(np.sum(self.weight))

    def eval(self, score: np.ndarray, objective) -> List[float]:
        raise NotImplementedError


_REGISTRY: Dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.metric_name] = cls
    return cls


def create_metric(name: str, config) -> Optional[Metric]:
    """Metric::CreateMetric (src/metric/metric.cpp:16). None for 'none'."""
    from . import multiclass, pointwise, rank  # noqa: F401
    if name in ("none", "null", "custom", "na", ""):
        return None
    if name not in _REGISTRY:
        Log.warning("Unknown metric type name: %s" % name)
        return None
    return _REGISTRY[name](config)
