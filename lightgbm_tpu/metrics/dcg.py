"""DCG / NDCG calculation utilities.

TPU-native rebuild of the reference DCGCalculator (src/metric/dcg_calculator.cpp,
include/LightGBM/metric.h:90-150): precomputed position discounts
1/log2(2+i) and label gains 2^l - 1 (DefaultLabelGain), max-DCG at k over
sorted labels, and vectorized per-query DCG evaluation used by both the
lambdarank objective and the ndcg/map metrics.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..utils.log import Log

# reference dcg_calculator.cpp: kMaxPosition = 10000 precomputed discounts;
# we compute on demand but keep a generous cache.
_DISCOUNT_CACHE = 1.0 / np.log2(2.0 + np.arange(65536, dtype=np.float64))


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 (DCGCalculator::DefaultLabelGain)."""
    return (np.power(2.0, np.arange(max_label + 1, dtype=np.float64)) - 1.0)


def get_discount(i):
    """Position discount 1/log2(2+i)."""
    return _DISCOUNT_CACHE[i]


def check_label(label: np.ndarray, num_label_gain: int) -> None:
    """DCGCalculator::CheckLabel: integer labels within label_gain range."""
    li = label.astype(np.int64)
    if np.any(np.abs(label - li) > 1e-6):
        Log.fatal("label should be int type (met %f) for ranking task"
                  % float(label[np.argmax(np.abs(label - li) > 1e-6)]))
    if li.min() < 0:
        Log.fatal("Label should be non-negative (met %d) for ranking task"
                  % int(li.min()))
    if li.max() >= num_label_gain:
        Log.fatal("Label %d is not less than the number of label mappings (%d)"
                  % (int(li.max()), num_label_gain))


def cal_max_dcg_at_k(k: int, label: np.ndarray, label_gain: np.ndarray) -> float:
    """Max DCG@k: labels sorted descending (DCGCalculator::CalMaxDCGAtK)."""
    n = len(label)
    k = min(k, n)
    if k <= 0:
        return 0.0
    s = np.sort(label.astype(np.int64))[::-1][:k]
    return float(np.sum(label_gain[s] * _DISCOUNT_CACHE[:k]))


def cal_dcg_at_k(k: int, label: np.ndarray, score: np.ndarray,
                 label_gain: np.ndarray) -> float:
    """DCG@k of the score-induced ranking (DCGCalculator::CalDCGAtK).
    Ties broken by stable sort on descending score (reference uses
    std::stable_sort with operator>)."""
    n = len(label)
    k = min(k, n)
    if k <= 0:
        return 0.0
    order = np.argsort(-score, kind="stable")[:k]
    lab = label.astype(np.int64)[order]
    return float(np.sum(label_gain[lab] * _DISCOUNT_CACHE[:k]))


def cal_dcg_at_ks(ks: Sequence[int], label: np.ndarray, score: np.ndarray,
                  label_gain: np.ndarray) -> List[float]:
    order = np.argsort(-score, kind="stable")
    lab = label.astype(np.int64)[order]
    gains = label_gain[lab] * _DISCOUNT_CACHE[:len(lab)]
    csum = np.cumsum(gains)
    out = []
    for k in ks:
        kk = min(k, len(lab))
        out.append(float(csum[kk - 1]) if kk > 0 else 0.0)
    return out


def cal_max_dcg_at_ks(ks: Sequence[int], label: np.ndarray,
                      label_gain: np.ndarray) -> List[float]:
    s = np.sort(label.astype(np.int64))[::-1]
    gains = label_gain[s] * _DISCOUNT_CACHE[:len(s)]
    csum = np.cumsum(gains)
    out = []
    for k in ks:
        kk = min(k, len(s))
        out.append(float(csum[kk - 1]) if kk > 0 else 0.0)
    return out
