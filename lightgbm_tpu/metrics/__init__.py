"""Evaluation metrics (src/metric/ rebuild, TPU-native)."""
from .base import Metric, create_metric
from . import multiclass, pointwise, rank  # noqa: F401

__all__ = ["Metric", "create_metric"]
