"""lightgbm_tpu.metrics"""
