"""Ranking metrics: NDCG@k and MAP@k.

TPU-native rebuild of src/metric/rank_metric.hpp:19-150 and
map_metric.hpp:20-140 over the DCG utilities in metrics/dcg.py; per-query
evaluation is host-side numpy (the reference's OpenMP-over-queries loop).
"""
from __future__ import annotations

import numpy as np

from ..utils.log import Log
from .base import Metric, register
from .dcg import (cal_dcg_at_ks, cal_max_dcg_at_ks, check_label,
                  default_label_gain)


def _default_eval_at(eval_at):
    # DCGCalculator::DefaultEvalAt
    return list(eval_at) if eval_at else [1, 2, 3, 4, 5]


@register
class NDCGMetric(Metric):
    metric_name = "ndcg"

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = _default_eval_at(config.eval_at)
        lg = list(config.label_gain)
        self.label_gain = (np.asarray(lg, dtype=np.float64) if lg
                           else default_label_gain())

    @property
    def names(self):
        return ["ndcg@%d" % k for k in self.eval_at]

    @property
    def factor_to_bigger_better(self):
        return 1.0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check_label(self.label, len(self.label_gain))
        if metadata.query_boundaries is None:
            Log.fatal("The NDCG metric requires query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = metadata.num_queries
        self.query_weights = metadata.query_weights
        if self.query_weights is None:
            self.sum_query_weights = float(self.num_queries)
        else:
            self.sum_query_weights = float(np.sum(self.query_weights))
        # cache inverse max DCG per query (rank_metric.hpp:57-75)
        self.inverse_max_dcgs = np.zeros((self.num_queries, len(self.eval_at)))
        qb = self.query_boundaries
        for q in range(self.num_queries):
            m = cal_max_dcg_at_ks(self.eval_at, self.label[qb[q]:qb[q + 1]],
                                  self.label_gain)
            for j, v in enumerate(m):
                self.inverse_max_dcgs[q, j] = 1.0 / v if v > 0.0 else -1.0

    def eval(self, score, objective):
        qb = self.query_boundaries
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            w = 1.0 if self.query_weights is None else self.query_weights[q]
            if self.inverse_max_dcgs[q, 0] <= 0.0:
                # all-negative query counts as NDCG = 1 (rank_metric.hpp:98)
                result += 1.0 * w
            else:
                dcg = cal_dcg_at_ks(self.eval_at, self.label[qb[q]:qb[q + 1]],
                                    score[qb[q]:qb[q + 1]], self.label_gain)
                result += np.asarray(dcg) * self.inverse_max_dcgs[q] * w
        return list(result / self.sum_query_weights)


@register
class MapMetric(Metric):
    metric_name = "map"

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = _default_eval_at(config.eval_at)

    @property
    def names(self):
        return ["map@%d" % k for k in self.eval_at]

    @property
    def factor_to_bigger_better(self):
        return 1.0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("For MAP metric, there should be query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = metadata.num_queries
        self.query_weights = metadata.query_weights
        if self.query_weights is None:
            self.sum_query_weights = float(self.num_queries)
        else:
            self.sum_query_weights = float(np.sum(self.query_weights))
        qb = self.query_boundaries
        self.npos_per_query = np.array([
            int(np.sum(self.label[qb[q]:qb[q + 1]] > 0.5))
            for q in range(self.num_queries)])

    def _map_at_ks(self, ks, npos, label, score):
        # map_metric.hpp:74-105
        order = np.argsort(-score, kind="stable")
        hits = (label[order] > 0.5)
        num_hit_cum = np.cumsum(hits)
        ap_terms = np.where(hits, num_hit_cum / (np.arange(len(order)) + 1.0), 0.0)
        sum_ap_cum = np.cumsum(ap_terms)
        out = []
        for k in ks:
            kk = min(k, len(order))
            if npos > 0:
                out.append(sum_ap_cum[kk - 1] / min(npos, kk) if kk > 0 else 0.0)
            else:
                out.append(1.0)
        return out

    def eval(self, score, objective):
        qb = self.query_boundaries
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            w = 1.0 if self.query_weights is None else self.query_weights[q]
            m = self._map_at_ks(self.eval_at, self.npos_per_query[q],
                                self.label[qb[q]:qb[q + 1]],
                                score[qb[q]:qb[q + 1]])
            result += np.asarray(m) * w
        return list(result / self.sum_query_weights)
