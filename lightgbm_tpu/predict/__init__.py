"""TPU-native inference subsystem.

The reference treats prediction as a first-class subsystem
(include/LightGBM/predictor.hpp); here it is three layers:

* :mod:`compile`  — pack a trained ensemble into padded, depth-bucketed
  SoA tensors (one-time, host side);
* :mod:`runtime`  — the jitted on-device traversal + objective transform
  (`TPUPredictor`), exact-parity f64 by default;
* :mod:`serve`    — power-of-two row-bucketed batching, chunking and
  local-mesh sharding for ragged serving traffic (`BatchServer`).

Selected through ``predict_device=tpu`` (config / Booster.predict kwarg);
the default ``cpu`` keeps the vectorized numpy walk in models/tree.py.
"""
from .compile import (CompiledEnsemble, EnsembleCompileError, TreeBucket,
                      compile_ensemble, quant_spec, quantize_ensemble)
from .runtime import TPUPredictor, make_device_transform
from .serve import BatchServer, place_padded

__all__ = ["CompiledEnsemble", "EnsembleCompileError", "TreeBucket",
           "compile_ensemble", "quant_spec", "quantize_ensemble",
           "TPUPredictor", "make_device_transform", "BatchServer",
           "place_padded"]
