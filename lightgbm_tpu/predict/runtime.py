"""Device-resident batched prediction runtime.

The execution half of the predict subsystem: ship a `CompiledEnsemble`'s
bucket tensors to device once, then serve batches as ONE jitted program —
a `lax.fori_loop` over tree levels of gather-select steps per depth bucket,
leaf-output accumulation across trees, and the objective transform
(sigmoid / softmax / exp / identity) on device.

Traversal semantics reproduce `Tree._decision` (models/tree.py) exactly:

* numerical: NaN -> 0 unless missing_type==NaN; zero/NaN routes to the
  recorded default direction; otherwise `fval <= threshold`;
* categorical: `int(fval)` bitset membership via word/shift tests against
  the bucket's flattened uint32 words; NaN counts as category 0 unless
  missing_type==NaN (-> right); negative values go right.

Accumulation order matters for parity: the host walk adds tree outputs to
each class accumulator in model order, so the runtime assembles the
`[T_total, rows]` contribution matrix in model order and folds it with a
sequential `lax.scan` over iterations — f64 sums are then bit-identical to
the numpy walk (`raw_score` parity is exact, not approximate). An f32 mode
(`dtype='f32'`) trades that for cheaper HBM/compute on chip; parity is then
pinned at 1e-6 by the tests.

Every distinct (rows, geometry) signature costs an XLA compile; callers
bound that by padding rows to power-of-two buckets — `TPUPredictor.predict`
does so by default and `serve.BatchServer` adds chunking + mesh sharding.
Compiles and served rows are pinned by telemetry counters under the
`predict` category.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..telemetry import events as telemetry
from .compile import (CompiledEnsemble, EnsembleCompileError, _next_pow2)

kZeroThreshold = 1e-35

# counter names (telemetry category "predict")
C_COMPILE = "predict::compile"
C_ROWS = "predict::tpu_rows"
C_BATCHES = "predict::tpu_batches"


def make_device_transform(objective) -> Optional[Callable]:
    """Device analog of ObjectiveFunction.convert_output for the common
    objectives (the reference Predictor's ConvertOutput hook). Returns None
    when the objective needs host conversion — the runtime then returns raw
    scores and the caller converts on host (still one device round trip)."""
    if objective is None:
        return None
    name = getattr(objective, "name", "")
    if name in ("none", "", "regression_l1", "huber", "fair", "quantile",
                "mape", "lambdarank", "rank_xendcg"):
        return lambda r: r
    if name == "regression":
        if getattr(objective, "sqrt", False):
            return lambda r: jnp.sign(r) * r * r
        return lambda r: r
    if name in ("binary", "multiclassova"):
        sig = float(getattr(objective, "sigmoid", 1.0))
        return lambda r: 1.0 / (1.0 + jnp.exp(-sig * r))
    if name == "multiclass":
        def softmax(r):
            m = jnp.max(r, axis=-1, keepdims=True)
            e = jnp.exp(r - m)
            return e / jnp.sum(e, axis=-1, keepdims=True)
        return softmax
    if name == "cross_entropy":
        return lambda r: 1.0 / (1.0 + jnp.exp(-r))
    if name == "cross_entropy_lambda":
        return lambda r: jnp.log1p(jnp.exp(r))
    if name in ("poisson", "gamma", "tweedie"):
        return jnp.exp
    return None


def _traverse_bucket(bucket_dev, X, depth: int):
    """One depth bucket: [T, rows] leaf indices after `depth` gather-select
    steps. X is [rows, F] (already on the traversal dtype)."""
    sf, thr, dt, left, right, cat_off, cat_nw, cat_words = bucket_dev
    T, N = sf.shape
    R = X.shape[0]
    XT = X.T                                  # [F, rows]
    rows = jnp.arange(R, dtype=jnp.int32)[None, :]
    node0 = jnp.zeros((T, R), dtype=jnp.int32)

    def step(_, node):
        nd = jnp.clip(node, 0, N - 1)
        feat = jnp.take_along_axis(sf, nd, axis=1)
        fv = XT[feat, rows]                   # [T, rows]
        th = jnp.take_along_axis(thr, nd, axis=1)
        d = jnp.take_along_axis(dt, nd, axis=1)
        is_cat = (d & 1) != 0
        mt = (d >> 2) & 3
        default_left = (d & 2) != 0
        isnan = jnp.isnan(fv)
        # numerical (Tree._decision numeric branch)
        fvn = jnp.where(isnan & (mt != 2), jnp.zeros_like(fv), fv)
        go_default = ((mt == 1) & (jnp.abs(fvn) <= kZeroThreshold)) \
            | ((mt == 2) & isnan)
        num_left = jnp.where(go_default, default_left, fvn <= th)
        # categorical (bitset membership, NaN->category 0, negatives right)
        int_fval = jnp.where(isnan, jnp.zeros_like(fv), fv).astype(jnp.int64)
        off = jnp.take_along_axis(cat_off, nd, axis=1).astype(jnp.int64)
        nw = jnp.take_along_axis(cat_nw, nd, axis=1).astype(jnp.int64)
        word = int_fval >> 5
        ok = (int_fval >= 0) & (word < nw)
        widx = off + jnp.clip(word, 0, jnp.maximum(nw - 1, 0))
        bits = cat_words[jnp.clip(widx, 0, cat_words.shape[0] - 1)]
        shift = (int_fval & 31).astype(jnp.uint32)
        hit = ok & (((bits >> shift) & jnp.uint32(1)) != 0)
        cat_left = hit & ~(isnan & (mt == 2)) & ~(fv < 0)
        go_left = jnp.where(is_cat, cat_left, num_left)
        nxt = jnp.where(go_left,
                        jnp.take_along_axis(left, nd, axis=1),
                        jnp.take_along_axis(right, nd, axis=1))
        return jnp.where(node >= 0, nxt, node)

    node = lax.fori_loop(0, depth, step, node0)
    # every row lands on a leaf within the bucket depth; clip for safety
    return jnp.clip(~node, 0, None).astype(jnp.int32)


class TPUPredictor:
    """Serve batched predictions for one compiled ensemble.

    One instance pins the ensemble tensors in HBM; `predict` pads rows to a
    power-of-two bucket (bounding recompiles to ~log2 of the batch-size
    range) and runs the jitted traversal. `predict_padded` is the raw
    entry for callers that manage padding themselves (serve.BatchServer).
    """

    def __init__(self, ensemble: CompiledEnsemble, objective=None,
                 dtype: str = "f64", min_rows: int = 128,
                 donate: Optional[bool] = None):
        if ensemble.num_trees % ensemble.num_tree_per_iteration != 0:
            raise EnsembleCompileError(
                "tree count %d is not a multiple of num_tree_per_iteration"
                " %d" % (ensemble.num_trees, ensemble.num_tree_per_iteration))
        self.ensemble = ensemble
        self.objective = objective
        self.num_class = ensemble.num_tree_per_iteration
        self.min_rows = max(int(min_rows), 1)
        self._dtype = jnp.float32 if dtype == "f32" else jnp.float64
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self._transform = make_device_transform(objective)
        self._dev_buckets = []
        for b in ensemble.buckets:
            self._dev_buckets.append((
                b.depth,
                jnp.asarray(b.tree_pos),
                (jnp.asarray(b.split_feature),
                 jnp.asarray(b.threshold, dtype=self._dtype),
                 jnp.asarray(b.decision_type),
                 jnp.asarray(b.left), jnp.asarray(b.right),
                 jnp.asarray(b.cat_offset), jnp.asarray(b.cat_nwords),
                 jnp.asarray(b.cat_words)),
                jnp.asarray(b.leaf_value, dtype=self._dtype)))
        donate_args = (0,) if donate else ()
        self._raw_fn = jax.jit(self._forward_raw,
                               static_argnums=(1,),
                               donate_argnums=donate_args)
        self._leaf_fn = jax.jit(self._forward_leaves,
                                donate_argnums=donate_args)
        self._seen_shapes = set()

    # -- jitted bodies --------------------------------------------------
    def _leaf_matrix(self, X):
        """[T_total, rows] leaf indices assembled in model order."""
        T_total = self.ensemble.num_trees
        leaves = jnp.zeros((T_total, X.shape[0]), dtype=jnp.int32)
        for depth, tree_pos, arrays, _leaf_value in self._dev_buckets:
            lf = _traverse_bucket(arrays, X, depth)
            leaves = leaves.at[tree_pos].set(lf)
        return leaves

    def _forward_raw(self, X, with_transform: bool):
        """[rows, K] scores; accumulation is a sequential per-iteration
        scan so the f64 sum order matches the host walk bit-for-bit."""
        T_total = self.ensemble.num_trees
        K = self.num_class
        contrib = jnp.zeros((T_total, X.shape[0]), dtype=self._dtype)
        for depth, tree_pos, arrays, leaf_value in self._dev_buckets:
            lf = _traverse_bucket(arrays, X, depth)
            contrib = contrib.at[tree_pos].set(
                jnp.take_along_axis(leaf_value, lf, axis=1))
        per_iter = contrib.reshape(T_total // K, K, X.shape[0])
        raw = lax.scan(lambda acc, c: (acc + c, None),
                       jnp.zeros((K, X.shape[0]), dtype=self._dtype),
                       per_iter)[0]
        raw = raw.T                                      # [rows, K]
        if with_transform and self._transform is not None:
            if self.ensemble.average_output:
                # inside jit only when a transform consumes it; the raw
                # path divides on host (XLA:CPU fast-math strength-reduces
                # /const to *recip, costing the bit-exact raw parity)
                raw = raw / max(T_total // K, 1)
            if K == 1:
                return self._transform(raw[:, 0])[:, None]
            return self._transform(raw)
        return raw

    def _forward_leaves(self, X):
        return self._leaf_matrix(X).T                    # [rows, T_total]

    # -- host API -------------------------------------------------------
    def _pad(self, X: np.ndarray):
        n = X.shape[0]
        n_pad = max(_next_pow2(n), self.min_rows)
        if n_pad == n:
            return X, n
        Xp = np.zeros((n_pad, X.shape[1]), dtype=X.dtype)
        Xp[:n] = X
        return Xp, n

    def _to_device(self, X: np.ndarray):
        key = (X.shape, "x")
        if key not in self._seen_shapes:
            self._seen_shapes.add(key)
            telemetry.count(C_COMPILE, 1, category="predict")
        return jnp.asarray(X, dtype=self._dtype)

    def dispatch_padded(self, X_dev, raw_score: bool = False):
        """Queue the traversal for device rows [n_pad, F] WITHOUT
        blocking: returns the in-flight device output array immediately
        (jax dispatch is async). The continuous-batching server admits
        and coalesces the next batch while this one runs; pair with
        :meth:`finalize_padded` at the one deliberate sync point."""
        return self._raw_fn(X_dev, not raw_score)

    def finalize_padded(self, out_dev, n_valid: int,
                        raw_score: bool = False):
        """Materialize a :meth:`dispatch_padded` result: the deliberate
        end-of-pipeline host sync, plus the host-side transform/average
        conversions and served-row accounting."""
        want_transform = not raw_score
        out = np.asarray(out_dev)[:n_valid]
        if not (want_transform and self._transform is not None) \
                and self.ensemble.average_output:
            # host-side numpy division: bit-parity with predict_raw
            out = out / max(self.ensemble.num_trees // self.num_class, 1)
        if want_transform and self._transform is None \
                and self.objective is not None:
            out = (self.objective.convert_output(out[:, 0])[:, None]
                   if self.num_class == 1
                   else self.objective.convert_output(out))
        telemetry.count(C_ROWS, n_valid, category="predict")
        telemetry.count(C_BATCHES, 1, category="predict")
        return out[:, 0] if self.num_class == 1 else out

    def predict_padded(self, X_dev, n_valid: int, raw_score: bool = False):
        """Device rows [n_pad, F] (padding rows are dropped) -> host
        predictions [n_valid(, K)]: dispatch + immediate finalize, the
        synchronous path (serve.BatchServer)."""
        return self.finalize_padded(
            self.dispatch_padded(X_dev, raw_score=raw_score),
            n_valid, raw_score=raw_score)

    def predict(self, X: np.ndarray, raw_score: bool = False) -> np.ndarray:
        X = np.ascontiguousarray(
            X, dtype=np.float64 if self._dtype == jnp.float64
            else np.float32)
        Xp, n = self._pad(X)
        return self.predict_padded(self._to_device(Xp), n,
                                   raw_score=raw_score)

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        X = np.ascontiguousarray(
            X, dtype=np.float64 if self._dtype == jnp.float64
            else np.float32)
        Xp, n = self._pad(X)
        key = (Xp.shape, "leaf")
        if key not in self._seen_shapes:
            self._seen_shapes.add(key)
            telemetry.count(C_COMPILE, 1, category="predict")
        out = np.asarray(self._leaf_fn(jnp.asarray(Xp, dtype=self._dtype)))
        telemetry.count(C_ROWS, n, category="predict")
        telemetry.count(C_BATCHES, 1, category="predict")
        return out[:n]
