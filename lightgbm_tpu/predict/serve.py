"""Bucketed batch-serving layer over the device predictor.

Serving traffic is ragged: every distinct row count is a distinct XLA
program, and an unbounded shape set means unbounded recompiles. This layer
pads each incoming batch up to a power-of-two row bucket between
`min_batch` and `max_batch`, so the steady-state program cache holds at
most ``ceil(log2(max_batch / min_batch)) + 1`` traversal executables no
matter what batch sizes arrive — the property the serve-layer test pins
via the `predict::serve_compile` / `predict::serve_bucket_hit` counters.

Batches larger than `max_batch` stream through in `max_batch` chunks
(bounded device memory). When more than one local device is visible and
the bucket divides evenly, the padded batch is placed row-sharded over the
local mesh (`NamedSharding` + jit — the pjit path), so one large request
fans out across chips; input buffers are donated on accelerator backends
(the padded copy is serving-owned, never reused).
"""
from __future__ import annotations


import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import events as telemetry
from .runtime import TPUPredictor, _next_pow2

C_SERVE_COMPILE = "predict::serve_compile"
C_SERVE_HIT = "predict::serve_bucket_hit"
C_SERVE_SHARDED = "predict::serve_sharded_batches"

ROWS_AXIS = "rows"


class BatchServer:
    """Pad-to-bucket batching + mesh fan-out for one TPUPredictor.

    ``min_batch``/``max_batch`` bound the power-of-two bucket ladder (and
    with it the compile count); ``shard_min_rows`` gates when a padded
    batch is worth sharding across the local devices.
    """

    def __init__(self, predictor: TPUPredictor, min_batch: int = 256,
                 max_batch: int = 1 << 16, shard_min_rows: int = 8192,
                 devices=None):
        if max_batch < min_batch:
            raise ValueError("max_batch %d < min_batch %d"
                             % (max_batch, min_batch))
        self.predictor = predictor
        self.min_batch = _next_pow2(max(int(min_batch), 1))
        self.max_batch = _next_pow2(int(max_batch))
        self.shard_min_rows = int(shard_min_rows)
        self.devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        self._mesh = (Mesh(np.array(self.devices), (ROWS_AXIS,))
                      if len(self.devices) > 1 else None)
        # instance-local serving stats: stats() must work (and the bench
        # must report true compile counts) even with telemetry off, where
        # events.count() is a no-op
        self._compiled_buckets = set()
        self._bucket_hits = 0
        self._sharded_batches = 0

    # ------------------------------------------------------------------
    def bucket_rows(self, n: int) -> int:
        """Smallest ladder bucket holding n rows (n <= max_batch)."""
        return min(max(_next_pow2(n), self.min_batch), self.max_batch)

    def max_compiles(self) -> int:
        """The compile bound the ladder guarantees."""
        return int(np.log2(self.max_batch // self.min_batch)) + 1

    def _place(self, Xp: np.ndarray):
        """Padded host batch -> device array, row-sharded over the local
        mesh when large enough and evenly divisible."""
        dt = np.float32 if self.predictor._dtype == jnp.float32 \
            else np.float64
        if (self._mesh is not None and Xp.shape[0] >= self.shard_min_rows
                and Xp.shape[0] % len(self.devices) == 0):
            self._sharded_batches += 1
            telemetry.count(C_SERVE_SHARDED, 1, category="predict")
            return jax.device_put(
                Xp.astype(dt, copy=False),
                NamedSharding(self._mesh, P(ROWS_AXIS, None)))
        return jnp.asarray(Xp, dtype=self.predictor._dtype)

    def _serve_chunk(self, X: np.ndarray, raw_score: bool) -> np.ndarray:
        n = X.shape[0]
        bucket = self.bucket_rows(n)
        if bucket in self._compiled_buckets:
            self._bucket_hits += 1
            telemetry.count(C_SERVE_HIT, 1, category="predict")
        else:
            self._compiled_buckets.add(bucket)
            telemetry.count(C_SERVE_COMPILE, 1, category="predict")
        Xp = np.zeros((bucket, X.shape[1]), dtype=np.float64)
        Xp[:n] = X
        return self.predictor.predict_padded(self._place(Xp), n,
                                             raw_score=raw_score)

    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        """Serve one request of any size; rows beyond max_batch stream in
        max_batch chunks."""
        X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[0] <= self.max_batch:
            return self._serve_chunk(X, raw_score)
        outs = [self._serve_chunk(X[i:i + self.max_batch], raw_score)
                for i in range(0, X.shape[0], self.max_batch)]
        return np.concatenate(outs, axis=0)

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Per-server serving stats (telemetry-independent; the same
        figures also land on the telemetry counters when enabled)."""
        return {
            "buckets_compiled": sorted(self._compiled_buckets),
            "compiles": len(self._compiled_buckets),
            "compile_bound": self.max_compiles(),
            "bucket_hits": self._bucket_hits,
            "sharded_batches": self._sharded_batches,
        }
