"""Bucketed batch-serving layer over the device predictor.

Serving traffic is ragged: every distinct row count is a distinct XLA
program, and an unbounded shape set means unbounded recompiles. This layer
pads each incoming batch up to a power-of-two row bucket between
`min_batch` and `max_batch`, so the steady-state program cache holds at
most ``ceil(log2(max_batch / min_batch)) + 1`` traversal executables no
matter what batch sizes arrive — the property the serve-layer test pins
via the `predict::serve_compile` / `predict::serve_bucket_hit` counters.

Batches larger than `max_batch` stream through in `max_batch` chunks
(bounded device memory). When more than one local device is visible and
the bucket divides evenly, the padded batch is placed row-sharded over the
local mesh (`NamedSharding` + jit — the pjit path), so one large request
fans out across chips; input buffers are donated on accelerator backends
(the padded copy is serving-owned, never reused).
"""
from __future__ import annotations


import threading
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import events as telemetry
from ..telemetry import histo as telemetry_histo
from ..telemetry.histo import Histogram
from .runtime import TPUPredictor, _next_pow2

C_SERVE_COMPILE = "predict::serve_compile"
C_SERVE_HIT = "predict::serve_bucket_hit"
C_SERVE_SHARDED = "predict::serve_sharded_batches"
H_E2E = "predict::e2e_latency"
H_QUEUE = "predict::queue_wait"
H_QDEPTH = "predict::queue_depth"

ROWS_AXIS = "rows"


def build_mesh(devices) -> "Mesh | None":
    """1-D row mesh over the given devices (None when a single device —
    plain placement is then strictly cheaper than a degenerate mesh)."""
    return Mesh(np.array(devices), (ROWS_AXIS,)) if len(devices) > 1 \
        else None


def place_padded(Xp: np.ndarray, dtype, mesh, devices,
                 shard_min_rows: int):
    """Padded host batch -> device array, row-sharded over the local
    mesh when large enough and evenly divisible. Returns (X_dev,
    sharded_flag); shared by the sync BatchServer and the async serving
    admission loop so both take the identical pjit fan-out path."""
    np_dt = np.float32 if dtype == jnp.float32 else np.float64
    if (mesh is not None and Xp.shape[0] >= shard_min_rows
            and Xp.shape[0] % len(devices) == 0):
        telemetry.count(C_SERVE_SHARDED, 1, category="predict")
        return jax.device_put(
            Xp.astype(np_dt, copy=False),
            NamedSharding(mesh, P(ROWS_AXIS, None))), True
    return jnp.asarray(Xp, dtype=dtype), False


class BatchServer:
    """Pad-to-bucket batching + mesh fan-out for one TPUPredictor.

    ``min_batch``/``max_batch`` bound the power-of-two bucket ladder (and
    with it the compile count); ``shard_min_rows`` gates when a padded
    batch is worth sharding across the local devices.
    """

    def __init__(self, predictor: TPUPredictor, min_batch: int = 256,
                 max_batch: int = 1 << 16, shard_min_rows: int = 8192,
                 devices=None):
        if max_batch < min_batch:
            raise ValueError("max_batch %d < min_batch %d"
                             % (max_batch, min_batch))
        self.predictor = predictor
        self.min_batch = _next_pow2(max(int(min_batch), 1))
        self.max_batch = _next_pow2(int(max_batch))
        self.shard_min_rows = int(shard_min_rows)
        self.devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        self._mesh = build_mesh(self.devices)
        # instance-local serving stats: stats() must work (and the bench
        # must report true compile counts) even with telemetry off, where
        # events.count() is a no-op
        self._compiled_buckets = set()
        self._bucket_hits = 0
        self._sharded_batches = 0
        # SLO histograms, same instance-local rule: per-request
        # end-to-end latency and queue wait (arrival -> service start,
        # when the caller supplies arrival_t — the open-loop Poisson
        # bench does). Mirrored into the global registry when telemetry
        # is on so they ride the metrics/prom exports.
        self._h_e2e = Histogram(H_E2E, unit="s", category="predict")
        self._h_queue = Histogram(H_QUEUE, unit="s", category="predict")
        # queue depth is sampled at ADMISSION as well as at service
        # start: depth that builds up between flushes (concurrent
        # callers stacking behind an in-service batch) is real queueing
        # the service-start sample alone never sees. _depth counts
        # requests admitted but not yet answered; the running max is the
        # stats() headline.
        self._h_qdepth = Histogram(H_QDEPTH, unit="req",
                                   category="predict")
        self._depth = 0
        self._qdepth_max = 0
        self._depth_lock = threading.Lock()

    # ------------------------------------------------------------------
    def bucket_rows(self, n: int) -> int:
        """Smallest ladder bucket holding n rows (n <= max_batch)."""
        return min(max(_next_pow2(n), self.min_batch), self.max_batch)

    def max_compiles(self) -> int:
        """The compile bound the ladder guarantees."""
        return int(np.log2(self.max_batch // self.min_batch)) + 1

    def _place(self, Xp: np.ndarray):
        """Padded host batch -> device array (module helper; counts
        sharded placements on this instance)."""
        X_dev, sharded = place_padded(Xp, self.predictor._dtype,
                                      self._mesh, self.devices,
                                      self.shard_min_rows)
        if sharded:
            with self._depth_lock:
                self._sharded_batches += 1
        return X_dev

    def _serve_chunk(self, X: np.ndarray, raw_score: bool) -> np.ndarray:
        n = X.shape[0]
        bucket = self.bucket_rows(n)
        with self._depth_lock:
            # check-then-act on the bucket set: two concurrent callers
            # hitting a fresh bucket must not both count a compile
            hit = bucket in self._compiled_buckets
            if hit:
                self._bucket_hits += 1
            else:
                self._compiled_buckets.add(bucket)
        telemetry.count(C_SERVE_HIT if hit else C_SERVE_COMPILE, 1,
                        category="predict")
        Xp = np.zeros((bucket, X.shape[1]), dtype=np.float64)
        Xp[:n] = X
        return self.predictor.predict_padded(self._place(Xp), n,
                                             raw_score=raw_score)

    def predict(self, X, raw_score: bool = False,
                arrival_t: float = None) -> np.ndarray:
        """Serve one request of any size; rows beyond max_batch stream in
        max_batch chunks.

        ``arrival_t`` (a ``time.perf_counter()`` timestamp) marks when
        the request entered the system: the gap to service start is the
        request's QUEUE WAIT, and end-to-end latency is measured from
        arrival rather than from service start — the numbers an SLO is
        written against. Omitted, queue wait records as 0 and e2e is
        pure service time."""
        d_adm = self._admit()
        telemetry_histo.observe(H_QDEPTH, float(d_adm), unit="req",
                                category="predict")
        t_start = time.perf_counter()
        try:
            q_wait = max(t_start - arrival_t, 0.0) \
                if arrival_t is not None else 0.0
            X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
            if X.ndim == 1:
                X = X.reshape(1, -1)
            if X.shape[0] <= self.max_batch:
                out = self._serve_chunk(X, raw_score)
            else:
                outs = [self._serve_chunk(X[i:i + self.max_batch],
                                          raw_score)
                        for i in range(0, X.shape[0], self.max_batch)]
                out = np.concatenate(outs, axis=0)
        finally:
            with self._depth_lock:
                self._depth -= 1
        e2e = time.perf_counter() - (arrival_t if arrival_t is not None
                                     else t_start)
        with self._depth_lock:
            # histogram record is a multi-field read-modify-write; the
            # instance histograms share _depth_lock with the depth state
            self._h_queue.record(q_wait)
            self._h_e2e.record(e2e)
        telemetry_histo.observe(H_QUEUE, q_wait, unit="s",
                                category="predict")
        telemetry_histo.observe(H_E2E, e2e, unit="s", category="predict")
        return out

    def _admit(self) -> int:
        """Count a request in; returns the post-admission depth — the
        admission-time queue-depth sample. Depth that builds up behind
        an in-service batch was invisible to service-start-only
        sampling (the bench's probe), so the server samples at both
        points and keeps the true max."""
        with self._depth_lock:
            self._depth += 1
            if self._depth > self._qdepth_max:
                self._qdepth_max = self._depth
            self._h_qdepth.record(float(self._depth))
            return self._depth

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Per-server serving stats (telemetry-independent; the same
        figures also land on the telemetry counters/histograms when
        enabled). `latency`/`queue_wait` carry the full histogram dicts;
        the p50/p99 shortcuts are what the bench SLO keys read."""
        with self._depth_lock:
            # consistent snapshot vs concurrent predict() callers (and
            # no set-changed-during-iteration on _compiled_buckets)
            return {
                "buckets_compiled": sorted(self._compiled_buckets),
                "compiles": len(self._compiled_buckets),
                "compile_bound": self.max_compiles(),
                "bucket_hits": self._bucket_hits,
                "sharded_batches": self._sharded_batches,
                "requests": self._h_e2e.count,
                "latency_p50": self._h_e2e.percentile(0.50),
                "latency_p99": self._h_e2e.percentile(0.99),
                "queue_wait_p99": self._h_queue.percentile(0.99),
                "qdepth_max": self._qdepth_max,
                "latency": self._h_e2e.to_dict(with_buckets=False),
                "queue_wait": self._h_queue.to_dict(with_buckets=False),
                "queue_depth": self._h_qdepth.to_dict(
                    with_buckets=False),
            }
