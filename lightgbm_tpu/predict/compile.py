"""Compile a trained ensemble into device-friendly SoA tensors.

The host predictor (models/tree.py) walks each tree per row with numpy
gathers — fine for a handful of rows, but the ROADMAP's serving story
("heavy traffic from millions of users") needs the traversal expressed as
dense tensor ops the XLA/TPU pipeline can fuse, the same recast the GBDT
inference accelerators make (Booster, arXiv:2011.02022; XGBoost's GPU
predictor, arXiv:1806.11248).

This module is the ahead-of-time half: it packs the per-tree SoA arrays
(`split_feature`, `threshold`, `decision_type`, children, leaf values,
categorical bitsets) into padded `[T, N]` tensors, with trees **bucketed by
next-power-of-two depth** so a shallow early tree does not force the whole
ensemble through a 64-level loop. Each bucket traverses in `depth` steps of
gather-select; the per-bucket tensors are what `runtime.TPUPredictor` ships
to HBM once and reuses for every batch.

Categorical thresholds keep the reference bitset representation: all bitset
words of a bucket flatten into one uint32 array with per-node (offset,
nwords) so membership stays a word/shift test on device — no per-node
ragged structures survive compilation.

Node encoding matches models/tree.py: child >= 0 is an internal node index,
child < 0 encodes leaf ~child. Traversal freezes at negative nodes, so
padded levels are no-ops for rows that already landed.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.log import LightGBMError

# refuse to ship absurd categorical blobs to HBM; the host walk handles the
# long tail (runtime falls back with a logged counter)
MAX_CAT_WORDS = 1 << 26


class EnsembleCompileError(LightGBMError):
    """Raised when the model geometry cannot be packed for the device
    runtime; callers fall back to the host walk (logged, never silent)."""


class TreeBucket(NamedTuple):
    """One depth bucket of the ensemble, padded to common geometry.

    T trees, N internal-node slots, L leaf slots, W categorical words.
    """

    depth: int                 # traversal steps (max leaf depth in bucket)
    tree_pos: np.ndarray       # [T] int32 — position in the model list
    split_feature: np.ndarray  # [T, N] int32
    threshold: np.ndarray      # [T, N] f64 (cat nodes: unused)
    decision_type: np.ndarray  # [T, N] int32 (widened from the int8 field)
    left: np.ndarray           # [T, N] int32
    right: np.ndarray          # [T, N] int32
    leaf_value: np.ndarray     # [T, L] f64
    cat_offset: np.ndarray     # [T, N] int32 into cat_words
    cat_nwords: np.ndarray     # [T, N] int32 (0 = not categorical)
    cat_words: np.ndarray      # [W] uint32 (>= 1 word, zero-padded)


class CompiledEnsemble(NamedTuple):
    buckets: Tuple[TreeBucket, ...]
    num_trees: int
    num_tree_per_iteration: int
    average_output: bool
    max_feature_idx: int


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def _pack_bucket(models: List, positions: List[int], depth: int) -> TreeBucket:
    T = len(positions)
    ni = max(max(models[p].num_leaves - 1 for p in positions), 1)
    nl = max(max(models[p].num_leaves for p in positions), 1)
    split_feature = np.zeros((T, ni), dtype=np.int32)
    threshold = np.zeros((T, ni), dtype=np.float64)
    decision_type = np.zeros((T, ni), dtype=np.int32)
    left = np.full((T, ni), -1, dtype=np.int32)
    right = np.full((T, ni), -1, dtype=np.int32)
    leaf_value = np.zeros((T, nl), dtype=np.float64)
    cat_offset = np.zeros((T, ni), dtype=np.int32)
    cat_nwords = np.zeros((T, ni), dtype=np.int32)
    words: List[int] = []
    for t, pos in enumerate(positions):
        tree = models[pos]
        n = tree.num_leaves
        leaf_value[t, :n] = tree.leaf_value[:n]
        if n <= 1:
            # stub: one synthetic numeric node routing everything to leaf 0
            continue
        k = n - 1
        split_feature[t, :k] = tree.split_feature[:k]
        threshold[t, :k] = tree.threshold[:k]
        decision_type[t, :k] = tree.decision_type[:k].astype(np.int32)
        left[t, :k] = tree.left_child[:k]
        right[t, :k] = tree.right_child[:k]
        for node in range(k):
            if not (int(tree.decision_type[node]) & 1):   # kCategoricalMask
                continue
            ci = int(tree.threshold[node])
            b0, b1 = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
            cat_offset[t, node] = len(words)
            cat_nwords[t, node] = b1 - b0
            words.extend(int(w) & 0xFFFFFFFF
                         for w in tree.cat_threshold[b0:b1])
    if len(words) > MAX_CAT_WORDS:
        raise EnsembleCompileError(
            "categorical bitsets too large for the device predictor "
            "(%d words > %d)" % (len(words), MAX_CAT_WORDS))
    cat_words = np.asarray(words or [0], dtype=np.uint32)
    return TreeBucket(
        depth=depth, tree_pos=np.asarray(positions, dtype=np.int32),
        split_feature=split_feature, threshold=threshold,
        decision_type=decision_type, left=left, right=right,
        leaf_value=leaf_value, cat_offset=cat_offset,
        cat_nwords=cat_nwords, cat_words=cat_words)


# the device predictor performs no deliberate IN-PROGRAM float
# narrowing: the f16 serving path (serving/quantized.py) snaps leaf and
# threshold VALUES onto the float16 grid on host before the tensors
# ship — the jitted traversal still computes at the runtime dtype, so
# the precision-flow audit sees no narrowing cast and this table stays
# empty. The grid itself is certified by analysis/quant_audit against
# quant_spec below (PREDICT_REL_BUDGET).
NARROW_OK = ()


def quant_spec(ensemble: Optional[CompiledEnsemble] = None,
               target: str = "float16", num_trees: int = 500) -> dict:
    """Declarative quantization spec for the f16 leaf/threshold serving
    tensors (ROADMAP item 3), the input analysis/quant_audit certifies
    BEFORE that PR lands.  With a compiled ensemble the caps come from
    the actual packed tensors; without one they are the documented
    contract defaults the certifier gates against (per-tree |leaf| <= 1
    after shrinkage, thresholds within the binned feature span)."""
    leaf_cap, thr_cap, n_trees = 1.0, 256.0, int(num_trees)
    if ensemble is not None:
        leaf_cap = max((float(np.abs(b.leaf_value).max())
                        for b in ensemble.buckets), default=1.0)
        thr_cap = max((float(np.abs(b.threshold).max())
                       for b in ensemble.buckets), default=1.0)
        n_trees = ensemble.num_trees
    return {
        "name": "leaf_%s" % target,
        "kind": "leaf",
        "target": target,
        "leaf_abs_max": leaf_cap,
        "threshold_abs_max": thr_cap,
        "num_trees": max(n_trees, 1),
    }


QUANT_TARGETS = ("float16", "f16")


def quantize_ensemble(ensemble: CompiledEnsemble,
                      target: str = "float16"
                      ) -> Tuple[CompiledEnsemble, dict]:
    """Snap an ensemble's leaf/threshold tensors onto the ``target``
    value grid (serving ROADMAP item 3). Returns (quantized ensemble,
    the :func:`quant_spec` describing it) — the caller is responsible
    for certifying the spec through ``analysis/quant_audit`` BEFORE
    serving the result (``serving/quantized.py`` is that seam; it
    refuses uncertified grids by certificate name).

    Only the float16 grid is buildable: every stored value rounds
    through ``np.float16`` (relative error <= 2^-11), then widens back
    so the runtime traverses at its usual dtype with halved effective
    mantissa content. Grids the certifier rejects at any geometry
    (int8) are not constructible here at all.
    """
    if target not in QUANT_TARGETS:
        raise EnsembleCompileError(
            "unsupported quantization target %r (buildable: %s; coarser "
            "grids fail quant_certify before reaching this point)"
            % (target, "/".join(QUANT_TARGETS)))
    spec = quant_spec(ensemble, target="float16")

    def _snap(a: np.ndarray) -> np.ndarray:
        # host-side value snap, not an in-program narrowing: the program
        # stays f64 end to end; admission requires the quant_audit
        # certificate against PREDICT_REL_BUDGET (serving/quantized.py)
        return a.astype(np.float16).astype(np.float64)  # graftlint: disable=JG010

    buckets = tuple(
        b._replace(threshold=_snap(b.threshold),
                   leaf_value=_snap(b.leaf_value))
        for b in ensemble.buckets)
    return ensemble._replace(buckets=buckets), spec


def compile_ensemble(models: List, num_tree_per_iteration: int = 1,
                     average_output: bool = False,
                     max_feature_idx: int = 0) -> CompiledEnsemble:
    """Pack host Trees into depth-bucketed device tensors.

    Raises EnsembleCompileError for geometry the runtime cannot serve
    (empty model, oversized categorical bitsets); the caller keeps the
    numpy walk as the logged fallback.
    """
    if not models:
        raise EnsembleCompileError("cannot compile an empty model")
    if any(m is None for m in models):
        raise EnsembleCompileError("model has unmaterialized trees")
    by_depth = {}
    for pos, tree in enumerate(models):
        d = _next_pow2(max(tree.max_depth(), 1))
        by_depth.setdefault(d, []).append(pos)
    buckets = tuple(_pack_bucket(models, by_depth[d], d)
                    for d in sorted(by_depth))
    return CompiledEnsemble(
        buckets=buckets, num_trees=len(models),
        num_tree_per_iteration=max(int(num_tree_per_iteration), 1),
        average_output=bool(average_output),
        max_feature_idx=int(max_feature_idx))
