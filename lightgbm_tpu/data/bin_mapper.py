"""Per-feature value -> bin discretization.

TPU-native rebuild of the reference BinMapper (include/LightGBM/bin.h:61-219,
src/io/bin.cpp). The bin-boundary algorithm reproduces the reference semantics
exactly (GreedyFindBin bin.cpp:79, FindBinWithZeroAsOneBin bin.cpp:257,
FindBinWithPredefinedBin bin.cpp:158, BinMapper::FindBin bin.cpp:326,
NeedFilter bin.cpp:55, ValueToBin bin.h:522) so that bin assignments — and
therefore trees — match the reference given the same samples. Host-side numpy;
the resulting boundaries drive a vectorized `value_to_bin` used to produce the
int8/int16 binned matrix that lives in TPU HBM.
"""
from __future__ import annotations

import math
from typing import Dict, List, Sequence

import numpy as np

from ..utils.log import Log

# reference include/LightGBM/meta.h:53
kZeroThreshold = 1e-35
# reference include/LightGBM/bin.h:39
kSparseThreshold = 0.7


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2

    _NAMES = {0: "None", 1: "Zero", 2: "NaN"}
    _FROM_NAME = {"none": 0, "zero": 1, "nan": 2}

    @classmethod
    def to_str(cls, v: int) -> str:
        return cls._NAMES[v]

    @classmethod
    def from_str(cls, s: str) -> int:
        return cls._FROM_NAME[s.strip().lower()]


class BinType:
    NUMERICAL = 0
    CATEGORICAL = 1


def _check_double_equal_ordered(a: float, b: float) -> bool:
    # reference common.h:889
    return b <= np.nextafter(a, np.inf)


def _double_upper_bound(a: float) -> float:
    # reference common.h:894
    return float(np.nextafter(a, np.inf))


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    num_distinct_values: int, max_bin: int,
                    total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy bin-boundary search; reference bin.cpp:79-156."""
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct_values <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct_values - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _double_upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
    else:
        if min_data_in_bin > 0:
            max_bin = min(max_bin, total_cnt // min_data_in_bin)
            max_bin = max(max_bin, 1)
        mean_bin_size = total_cnt / max_bin
        n = num_distinct_values
        cnts = np.asarray(counts[:n], dtype=np.int64)
        is_big = cnts >= mean_bin_size
        rest_bin_cnt = max_bin - int(np.count_nonzero(is_big))
        init_rest = int(total_cnt) - int(cnts[is_big].sum())
        mean_bin_size = init_rest / rest_bin_cnt if rest_bin_cnt else math.inf

        # The boundary walk is sequential, but between boundaries nothing
        # changes: the next stop is the earliest of (first big value),
        # (prefix count reaching mean_bin_size), (value preceding a big one
        # once half a bin has accumulated). Each is a sorted-array lookup, so
        # the walk costs O(max_bin log n) instead of a Python loop over every
        # distinct value.
        prefix = np.cumsum(cnts)                       # [n]
        # float copy for the threshold lookups: comparing an int array
        # against a float target would silently convert the whole array
        # per searchsorted call (sample counts are < 2^53, so exact)
        prefix_f = prefix.astype(np.float64)
        small_prefix = np.cumsum(np.where(is_big, 0, cnts))
        big_idx = np.nonzero(is_big)[0]

        upper_bounds = []
        lower_bounds = [distinct_values[0]]
        bin_cnt = 0
        seg = 0                                        # first index of segment
        while seg <= n - 2:
            base = int(prefix[seg - 1]) if seg > 0 else 0
            j = np.searchsorted(big_idx, seg, side="left")
            i_a = int(big_idx[j]) if j < len(big_idx) else n
            i_b = int(np.searchsorted(prefix_f, base + mean_bin_size,
                                      side="left"))
            t_half = max(1.0, mean_bin_size * np.float32(0.5))
            pos_h = int(np.searchsorted(prefix_f, base + t_half, side="left"))
            jc = np.searchsorted(big_idx, max(seg, pos_h) + 1, side="left")
            i_c = int(big_idx[jc]) - 1 if jc < len(big_idx) else n
            stop = min(i_a, i_b, i_c)
            if stop > n - 2:
                break
            upper_bounds.append(distinct_values[stop])
            bin_cnt += 1
            lower_bounds.append(distinct_values[stop + 1])
            if bin_cnt >= max_bin - 1:
                break
            if not is_big[stop]:
                rest_bin_cnt -= 1
                rest = init_rest - int(small_prefix[stop])
                mean_bin_size = rest / rest_bin_cnt if rest_bin_cnt else math.inf
            seg = stop + 1
        bin_cnt += 1
        for i in range(bin_cnt - 1):
            val = _double_upper_bound((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
            if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                bin_upper_bound.append(val)
        bin_upper_bound.append(math.inf)
    return bin_upper_bound


def _find_bin_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                              num_distinct_values: int, max_bin: int,
                              total_sample_cnt: int, min_data_in_bin: int) -> List[float]:
    """Zero gets its own bin; reference bin.cpp:257-313."""
    bin_upper_bound: List[float] = []
    dv = distinct_values[:num_distinct_values]
    ct = counts[:num_distinct_values]
    left_mask = dv <= -kZeroThreshold
    right_mask = dv > kZeroThreshold
    left_cnt_data = int(ct[left_mask].sum())
    right_cnt_data = int(ct[right_mask].sum())
    cnt_zero = int(total_sample_cnt) - left_cnt_data - right_cnt_data

    nz = np.nonzero(dv > -kZeroThreshold)[0]
    left_cnt = int(nz[0]) if len(nz) else num_distinct_values

    if left_cnt > 0 and max_bin > 1:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1)) if denom else 1
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = greedy_find_bin(dv, ct, left_cnt, left_max_bin,
                                          left_cnt_data, min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -kZeroThreshold

    nz = np.nonzero(dv[left_cnt:] > kZeroThreshold)[0]
    right_start = int(nz[0]) + left_cnt if len(nz) else -1

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(dv[right_start:], ct[right_start:],
                                       num_distinct_values - right_start,
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(kZeroThreshold)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _find_bin_with_predefined(distinct_values: np.ndarray, counts: np.ndarray,
                              num_distinct_values: int, max_bin: int,
                              total_sample_cnt: int, min_data_in_bin: int,
                              forced_upper_bounds: Sequence[float]) -> List[float]:
    """Forced bin boundaries (forcedbins_filename); reference bin.cpp:158-255."""
    dv = distinct_values[:num_distinct_values]
    left_cnt = num_distinct_values
    nz = np.nonzero(dv > -kZeroThreshold)[0]
    if len(nz):
        left_cnt = int(nz[0])
    nz = np.nonzero(dv[left_cnt:] > kZeroThreshold)[0]
    right_start = int(nz[0]) + left_cnt if len(nz) else -1

    bin_upper_bound: List[float] = []
    if max_bin == 2:
        bin_upper_bound.append(kZeroThreshold if left_cnt == 0 else -kZeroThreshold)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-kZeroThreshold)
        if right_start >= 0:
            bin_upper_bound.append(kZeroThreshold)
    bin_upper_bound.append(math.inf)

    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for b in forced_upper_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > kZeroThreshold:
            bin_upper_bound.append(float(b))
            num_inserted += 1
    bin_upper_bound.sort()

    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_fixed = len(bin_upper_bound)
    for i in range(n_fixed):
        cnt_in_bin = 0
        distinct_cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < num_distinct_values and dv[value_ind] < bin_upper_bound[i]:
            cnt_in_bin += int(counts[value_ind])
            distinct_cnt_in_bin += 1
            value_ind += 1
        bins_remaining = max_bin - n_fixed - len(bounds_to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / total_sample_cnt))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_fixed - 1:
            num_sub_bins = bins_remaining + 1
        if distinct_cnt_in_bin > 0:
            new_bounds = greedy_find_bin(dv[bin_start:], counts[bin_start:],
                                         distinct_cnt_in_bin, num_sub_bins,
                                         cnt_in_bin, min_data_in_bin)
            bounds_to_add.extend(new_bounds[:-1])  # last bound is inf
    bin_upper_bound.extend(bounds_to_add)
    bin_upper_bound.sort()
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def find_bin_bounds(distinct_values, counts, num_distinct_values, max_bin,
                    total_sample_cnt, min_data_in_bin, forced_upper_bounds=()):
    if len(forced_upper_bounds) == 0:
        return _find_bin_zero_as_one_bin(distinct_values, counts, num_distinct_values,
                                         max_bin, total_sample_cnt, min_data_in_bin)
    return _find_bin_with_predefined(distinct_values, counts, num_distinct_values,
                                     max_bin, total_sample_cnt, min_data_in_bin,
                                     forced_upper_bounds)


def _need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """True if no split on this feature could satisfy min counts; bin.cpp:55-77."""
    if bin_type == BinType.NUMERICAL:
        sum_left = np.cumsum(cnt_in_bin[:-1])
        ok = (sum_left >= filter_cnt) & (total_cnt - sum_left >= filter_cnt)
        return not bool(ok.any())
    if len(cnt_in_bin) <= 2:
        for i in range(len(cnt_in_bin) - 1):
            sum_left = int(cnt_in_bin[i])
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    return False


class BinMapper:
    """Feature discretizer; mirrors reference BinMapper state (bin.h:61-219)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MissingType.NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BinType.NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.categorical_2_bin: Dict[int, int] = {}
        self.bin_2_categorical: List[int] = []
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int, min_split_data: int, pre_filter: bool,
                 bin_type: int = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False,
                 forced_upper_bounds: Sequence[float] = ()) -> None:
        """Compute bin boundaries from sampled non-zero values.

        `values` are the sampled values EXCLUDING implicit zeros (the reference
        sampling stores only non-zero entries; zero count is inferred from
        total_sample_cnt). NaNs may be present. Reference bin.cpp:326-533.
        """
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]
        num_sample_values = len(values) + na_cnt

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NONE if na_cnt == 0 else MissingType.NAN
        if self.missing_type != MissingType.NAN:
            # reference bin.cpp:330-353: na_cnt stays 0 outside the NaN branch,
            # so stripped NaNs are counted into zero_cnt (they bin as zero)
            na_cnt = 0
        n_values = len(values)

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - n_values - na_cnt)

        # distinct values with 1-ulp merging (larger value kept); bin.cpp:354-390
        values = np.sort(values, kind="stable")
        if n_values > 0:
            new_group = np.empty(n_values, dtype=bool)
            new_group[0] = True
            if n_values > 1:
                new_group[1:] = values[1:] > np.nextafter(values[:-1], np.inf)
            group_idx = np.nonzero(new_group)[0]
            # distinct value is the last (largest) member of each run
            end_idx = np.append(group_idx[1:], n_values) - 1
            dvals = values[end_idx]
            dcnts = np.diff(np.append(group_idx, n_values))
        else:
            dvals = np.empty(0)
            dcnts = np.empty(0, dtype=np.int64)

        # insert the implicit zero (stripped by sampling) into the sorted
        # distinct list: before positives / between sign change / after
        # negatives — the sign-change insert happens even at zero_cnt == 0
        if n_values == 0:
            dv_arr = np.array([0.0])
            ct_arr = np.array([max(zero_cnt, 0)], dtype=np.int64)
        else:
            pos0 = int(np.searchsorted(dvals, 0.0, side="left"))
            if pos0 == 0:
                insert = zero_cnt > 0 and dvals[0] > 0.0
            elif pos0 == len(dvals):
                insert = zero_cnt > 0 and dvals[-1] < 0.0
            else:
                insert = dvals[pos0 - 1] < 0.0 and dvals[pos0] > 0.0
            if insert:
                dv_arr = np.insert(dvals, pos0, 0.0)
                ct_arr = np.insert(dcnts.astype(np.int64), pos0, zero_cnt)
            else:
                dv_arr = dvals
                ct_arr = dcnts.astype(np.int64)
        distinct_values = dv_arr
        counts = ct_arr
        # NOTE: when sampled values contain exact 0.0 runs the reference counted
        # them in-place; our caller strips zeros, so implicit-zero insertion above
        # is the only zero source (matches dataset_loader's non-zero sampling).

        self.min_val = float(distinct_values[0])
        self.max_val = float(distinct_values[-1])
        dv = np.asarray(distinct_values)
        ct = np.asarray(counts, dtype=np.int64)
        num_distinct_values = len(dv)

        cnt_in_bin: np.ndarray
        if bin_type == BinType.NUMERICAL:
            if self.missing_type == MissingType.ZERO:
                bounds = find_bin_bounds(dv, ct, num_distinct_values, max_bin,
                                         total_sample_cnt, min_data_in_bin,
                                         forced_upper_bounds)
                if len(bounds) == 2:
                    self.missing_type = MissingType.NONE
            elif self.missing_type == MissingType.NONE:
                bounds = find_bin_bounds(dv, ct, num_distinct_values, max_bin,
                                         total_sample_cnt, min_data_in_bin,
                                         forced_upper_bounds)
            else:
                bounds = find_bin_bounds(dv, ct, num_distinct_values, max_bin - 1,
                                         total_sample_cnt - na_cnt, min_data_in_bin,
                                         forced_upper_bounds)
                bounds = list(bounds) + [math.nan]
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            # count per bin; bin.cpp:411-423
            n_search = self.num_bin - (1 if self.missing_type == MissingType.NAN else 0)
            search_bounds = self.bin_upper_bound[:n_search]
            idx = np.searchsorted(search_bounds, dv, side="left")
            idx = np.minimum(idx, n_search - 1)
            cnt_in_bin = np.bincount(idx, weights=ct, minlength=self.num_bin).astype(np.int64)
            if self.missing_type == MissingType.NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical; bin.cpp:425-497
            dvi: List[int] = []
            cti: List[int] = []
            for v, c in zip(dv, ct):
                iv = int(v)
                if iv < 0:
                    na_cnt += int(c)
                    Log.warning("Met negative value in categorical features, "
                                "will convert it to NaN")
                else:
                    if not dvi or iv != dvi[-1]:
                        dvi.append(iv)
                        cti.append(int(c))
                    else:
                        cti[-1] += int(c)
            self.num_bin = 0
            rest_cnt = int(total_sample_cnt - na_cnt)
            cnt_in_bin = np.zeros(0, dtype=np.int64)
            if rest_cnt > 0:
                if dvi and dvi[-1] // 100 > len(dvi):
                    Log.warning("Met categorical feature which contains sparse values. "
                                "Consider renumbering to consecutive integers "
                                "started from zero")
                order = sorted(range(len(cti)), key=lambda i: -cti[i])
                cti = [cti[i] for i in order]
                dvi = [dvi[i] for i in order]
                if dvi and dvi[0] == 0:
                    if len(cti) == 1:
                        cti.append(0)
                        dvi.append(dvi[0] + 1)
                    cti[0], cti[1] = cti[1], cti[0]
                    dvi[0], dvi[1] = dvi[1], dvi[0]
                cut_cnt = int((total_sample_cnt - na_cnt) * np.float32(0.99))
                cur_cat = 0
                self.categorical_2_bin = {}
                self.bin_2_categorical = []
                used_cnt = 0
                max_bin = min(len(dvi), max_bin)
                cib: List[int] = []
                while cur_cat < len(dvi) and (used_cnt < cut_cnt or self.num_bin < max_bin):
                    if cti[cur_cat] < min_data_in_bin and cur_cat > 1:
                        break
                    self.bin_2_categorical.append(dvi[cur_cat])
                    self.categorical_2_bin[dvi[cur_cat]] = self.num_bin
                    used_cnt += cti[cur_cat]
                    cib.append(cti[cur_cat])
                    self.num_bin += 1
                    cur_cat += 1
                if cur_cat == len(dvi) and na_cnt > 0:
                    self.bin_2_categorical.append(-1)
                    self.categorical_2_bin[-1] = self.num_bin
                    cib.append(0)
                    self.num_bin += 1
                if cur_cat == len(dvi) and na_cnt == 0:
                    self.missing_type = MissingType.NONE
                else:
                    self.missing_type = MissingType.NAN
                if cib:
                    cib[-1] += int(total_sample_cnt - used_cnt)
                cnt_in_bin = np.asarray(cib, dtype=np.int64)

        # trivial / filter / most_freq; bin.cpp:499-533
        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and \
                _need_filter(cnt_in_bin, int(total_sample_cnt), min_split_data, bin_type):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(np.array([0.0]))[0])
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            if bin_type == BinType.CATEGORICAL and self.most_freq_bin == 0:
                assert self.num_bin > 1
                self.most_freq_bin = 1
            max_sparse_rate = float(cnt_in_bin[self.most_freq_bin]) / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < kSparseThreshold:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = float(cnt_in_bin[self.most_freq_bin]) / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    # ------------------------------------------------------------------
    def categorical_lut(self) -> np.ndarray:
        """Dense category -> bin lookup table; indices outside it (and
        negatives/NaN) map to num_bin - 1. Shared by the numpy and native
        (binrows.cpp) binning paths so their semantics cannot diverge."""
        lut_size = max([k for k in self.categorical_2_bin] or [0]) + 2
        lut = np.full(lut_size, self.num_bin - 1, dtype=np.int32)
        for k, b in self.categorical_2_bin.items():
            if k >= 0:
                lut[k] = b
        return lut

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference bin.h:522-556 binary search)."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(values.shape, dtype=np.int32)
        nan_mask = np.isnan(values)
        if self.bin_type == BinType.NUMERICAL:
            v = np.where(nan_mask, 0.0, values)
            n_search = self.num_bin - (1 if self.missing_type == MissingType.NAN else 0)
            bounds = self.bin_upper_bound[:n_search]
            out = np.searchsorted(bounds, v, side="left").astype(np.int32)
            out = np.minimum(out, n_search - 1)
            if self.missing_type == MissingType.NAN:
                out[nan_mask] = self.num_bin - 1
        else:
            iv = np.where(nan_mask, -1, np.where(np.isfinite(values), values, -1)).astype(np.int64)
            lut = self.categorical_lut()
            lut_size = len(lut)
            bad = (iv < 0) | (iv >= lut_size)
            out = np.where(bad, self.num_bin - 1, lut[np.clip(iv, 0, lut_size - 1)]).astype(np.int32)
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative value of a bin (categorical: the category)."""
        if self.bin_type == BinType.CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    @property
    def is_categorical(self) -> bool:
        return self.bin_type == BinType.CATEGORICAL

    # -- serialization (for distributed binning allgather & binary cache) --
    def to_state(self) -> dict:
        return {
            "num_bin": self.num_bin, "missing_type": self.missing_type,
            "is_trivial": self.is_trivial, "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val, "max_val": self.max_val,
            "default_bin": self.default_bin, "most_freq_bin": self.most_freq_bin,
        }

    @classmethod
    def from_state(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        return m
