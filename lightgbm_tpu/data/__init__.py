"""lightgbm_tpu.data"""
