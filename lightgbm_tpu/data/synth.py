"""Synthetic benchmark datasets (the canonical home; bench.py re-exports).

The shapes mirror the reference's experiment sets (docs/Experiments.rst):
HIGGS-like continuous kinematics for the throughput north star. Kept inside
the package so the bench scripts, the profiling CLI
(``python -m lightgbm_tpu.profile``) and tests all draw the same data
without duplicating generator logic at the repo top level.
"""
from __future__ import annotations

import numpy as np


def make_higgs_like(n_rows: int, n_features: int = 28, seed: int = 7):
    """Synthetic stand-in for HIGGS: continuous kinematic-like features,
    nonlinear decision boundary, ~53/47 class balance like the real set."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    # a few derived-feature couplings like HIGGS's high-level features
    X[:, 21] = np.abs(X[:, 0] * X[:, 1]) + 0.3 * X[:, 21]
    X[:, 22] = X[:, 2] ** 2 + X[:, 3] ** 2 + 0.3 * X[:, 22]
    logit = (0.8 * X[:, 0] - 0.5 * X[:, 1] + 0.4 * X[:, 21]
             - 0.3 * X[:, 22] + 0.5 * np.tanh(X[:, 4] * X[:, 5]))
    y = (logit + rng.logistic(size=n_rows).astype(np.float32) * 0.8 > 0.0)
    return X.astype(np.float64), y.astype(np.float64)
