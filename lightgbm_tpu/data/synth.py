"""Synthetic benchmark datasets (the canonical home; bench.py re-exports).

The shapes mirror the reference's experiment sets (docs/Experiments.rst):
HIGGS-like continuous kinematics for the throughput north star, the
MS-LTR and Yahoo-LTR ranking shapes, the Expo EFB-bundled one-hot shape,
and the Allstate sparse wide-one-hot shape. Kept inside the package so
the bench scripts, the profiling CLI (``python -m lightgbm_tpu.profile``)
and tests all draw the same data without duplicating generator logic at
the repo top level.
"""
from __future__ import annotations

import numpy as np


def make_higgs_like(n_rows: int, n_features: int = 28, seed: int = 7):
    """Synthetic stand-in for HIGGS: continuous kinematic-like features,
    nonlinear decision boundary, ~53/47 class balance like the real set."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    # a few derived-feature couplings like HIGGS's high-level features
    X[:, 21] = np.abs(X[:, 0] * X[:, 1]) + 0.3 * X[:, 21]
    X[:, 22] = X[:, 2] ** 2 + X[:, 3] ** 2 + 0.3 * X[:, 22]
    logit = (0.8 * X[:, 0] - 0.5 * X[:, 1] + 0.4 * X[:, 21]
             - 0.3 * X[:, 22] + 0.5 * np.tanh(X[:, 4] * X[:, 5]))
    y = (logit + rng.logistic(size=n_rows).astype(np.float32) * 0.8 > 0.0)
    return X.astype(np.float64), y.astype(np.float64)


def make_ltr_like(n_rows=2_270_000, n_feat=137, docs_per_query=73, seed=3):
    """MSLR-WEB30K-shaped synthetic LTR set: graded 0-4 relevance driven by
    a sparse linear + nonlinear signal, fixed-size query groups."""
    rng = np.random.default_rng(seed)
    n_q = n_rows // docs_per_query
    n_rows = n_q * docs_per_query
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    w = np.zeros(n_feat)
    w[:20] = rng.normal(size=20)
    sig = X @ w + 0.7 * np.tanh(X[:, 20] * X[:, 21]) \
        + rng.logistic(size=n_rows) * 1.2
    # per-query grading to 0..4 by quantile
    sig = sig.reshape(n_q, docs_per_query)
    q = np.quantile(sig, [0.55, 0.75, 0.90, 0.97], axis=1)
    lab = (sig > q[0][:, None]).astype(np.int32)
    for k in range(1, 4):
        lab += sig > q[k][:, None]
    group = np.full(n_q, docs_per_query, dtype=np.int32)
    return X.astype(np.float64), lab.reshape(-1).astype(np.float64), group


def make_yahoo_like(n_rows=473_134, n_feat=700, docs_per_query=24, seed=11):
    """Yahoo LTR set1-shaped synthetic: 473k docs x 700 dense features in
    ~24-doc queries (docs/Experiments.rst lists 473,134 x 700)."""
    return make_ltr_like(n_rows, n_feat=n_feat,
                         docs_per_query=docs_per_query, seed=seed)


def make_expo_like(n_rows=2_000_000, seed=0):
    """Expo-shaped synthetic: a few dense numerics plus one-hot blocks
    that EFB bundles into a handful of byte groups."""
    rng = np.random.default_rng(seed)
    nd = 8
    blocks = [50, 30, 24, 24, 12, 300, 200]
    Xd = rng.normal(size=(n_rows, nd)).astype(np.float32)
    cols = [Xd]
    sig = Xd[:, 0] * 0.5
    for card in blocks:
        ids = rng.integers(0, card, n_rows)
        oh = np.zeros((n_rows, card), np.float32)
        oh[np.arange(n_rows), ids] = 1.0
        cols.append(oh)
        sig = sig + (ids % 7 == 0) * 0.4
    X = np.concatenate(cols, axis=1)
    y = (sig + rng.logistic(size=n_rows) * 0.7 > 0.3)
    # f32 halves the ~10GB peak a dense f64 one-hot matrix would cost;
    # the binner accepts any float input
    return X, y.astype(np.float64)


def make_allstate_like(n_rows=1_000_000, seed=5):
    """Allstate-shaped synthetic (docs/Experiments.rst: 13.18M x 4228
    mostly one-hot columns): ~55 categorical blocks one-hot-expanded to
    ~4.1k binary features plus a few numerics, returned as a scipy CSR so
    the dense matrix is never materialized (the sparse-ingest path bins it
    chunk-wise; EFB re-bundles each block into byte groups)."""
    import scipy.sparse as sp
    rng = np.random.default_rng(seed)
    # cardinalities roughly log-spaced like an insurance schema: a few
    # huge blocks, many small ones — ~4.1k one-hot columns total
    # 4218 one-hot columns + 8 numerics ~= the 4228 reference columns
    cards = ([900, 600, 500, 350, 300, 250, 180, 120, 100, 80, 60, 50]
             + [40] * 6 + [25] * 8 + [12] * 12 + [7] * 12 + [4] * 15)
    nd = 8                       # leading dense numeric columns
    n_feat = nd + sum(cards)
    dense = rng.normal(size=(n_rows, nd)).astype(np.float32)
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nd)
    cols = np.tile(np.arange(nd, dtype=np.int64), n_rows)
    data = [dense.reshape(-1)]
    col_blocks = [cols]
    row_blocks = [rows]
    sig = dense[:, 0] * 0.4 - 0.3 * dense[:, 1]
    base = nd
    ar = np.arange(n_rows, dtype=np.int64)
    for card in cards:
        ids = rng.integers(0, card, n_rows)
        row_blocks.append(ar)
        col_blocks.append(base + ids.astype(np.int64))
        data.append(np.ones(n_rows, np.float32))
        sig = sig + (ids % 5 == 0) * (0.5 if card >= 100 else 0.15)
        base += card
    X = sp.csr_matrix(
        (np.concatenate(data),
         (np.concatenate(row_blocks), np.concatenate(col_blocks))),
        shape=(n_rows, n_feat))
    y = (sig + rng.logistic(size=n_rows).astype(np.float32) * 0.8 > 0.6)
    return X, y.astype(np.float64)
