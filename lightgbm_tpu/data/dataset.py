"""Binned training dataset: host-side construction, device-side layout.

TPU-native rebuild of the reference data layer (include/LightGBM/dataset.h:333,
src/io/dataset.cpp, feature_group.h:21). Differences by design:

  * The binned matrix is one dense [num_data, num_groups] integer array of
    group-local bins living in TPU HBM (row-sharded over the mesh in
    distributed mode) instead of per-group Bin objects with dense/sparse/4-bit
    variants — HBM bandwidth is the constraint, so the narrowest dtype that
    fits a group's bin count is chosen (uint8/uint16/int32).
  * EFB (exclusive feature bundling, reference src/io/dataset.cpp:41-314)
    keeps its greedy conflict-bounded grouping, but a bundled group reserves
    group-local bin 0 as the "all features at default" sentinel, and each
    sub-feature keeps its full local bin range. Rows never write a
    sub-feature's most_freq bin; histograms for bundled features are repaired
    from leaf totals exactly like the reference's FixHistogram
    (src/io/dataset.cpp:1410) — see ops/split.fix_histogram.
  * Metadata (labels/weights/query boundaries/init_score) mirrors
    include/LightGBM/dataset.h:41 and src/io/metadata.cpp.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import timer
from ..utils.log import Log
from .bin_mapper import BinMapper, BinType, kZeroThreshold

MAX_GROUP_BINS = 256  # keep bundled groups addressable by uint8 (GPU ref: 256)


class Metadata:
    """Labels, weights, query boundaries, init scores (dataset.h:41)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # [nq+1] int32
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal("Length of label (%d) != num_data (%d)"
                      % (len(label), self.num_data))
        self.label = label

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            Log.fatal("Length of weight (%d) != num_data (%d)"
                      % (len(weight), self.num_data))
        self.weight = weight

    def set_query(self, group) -> None:
        """group: per-query sizes (LightGBM convention) or boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        if group.sum() == self.num_data:
            self.query_boundaries = np.concatenate(
                [[0], np.cumsum(group)]).astype(np.int32)
        elif len(group) and group[0] == 0 and group[-1] == self.num_data:
            self.query_boundaries = group.astype(np.int32)
        else:
            Log.fatal("Sum of query counts (%d) != num_data (%d)"
                      % (group.sum(), self.num_data))

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.ascontiguousarray(
            init_score, dtype=np.float64).reshape(-1)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    @property
    def query_weights(self) -> Optional[np.ndarray]:
        """Mean row weight per query (Metadata::LoadQueryWeights,
        src/io/metadata.cpp:455-469); None without weights or queries."""
        if self.weight is None or self.query_boundaries is None:
            return None
        qb = self.query_boundaries
        sums = np.add.reduceat(self.weight.astype(np.float64), qb[:-1])
        return (sums / np.diff(qb)).astype(np.float32)


class SampleCols:
    """Per-feature sampled (values, row-indices) — the reference's own
    sample representation (DatasetLoader::CostructFromSampleData takes
    sample_values/sample_indices per feature, src/io/dataset_loader.cpp:528)
    — so sparse inputs sample without densifying."""

    def __init__(self, values, rows, total):
        self.values = values
        self.rows = rows
        self.total = total


def _sample_data(X: np.ndarray, sample_cnt: int, seed: int) -> np.ndarray:
    n = X.shape[0]
    if n <= sample_cnt:
        return X
    rng = np.random.default_rng(seed)
    idx = rng.choice(n, size=sample_cnt, replace=False)
    idx.sort()
    return X[idx]


def _greedy_bundle(nonzero_masks: List[np.ndarray], order: List[int],
                   num_bins: List[int], total_sample: int,
                   max_conflict_cnt: int) -> List[List[int]]:
    """Greedy conflict-bounded bundling (reference FindGroups,
    src/io/dataset.cpp:97-234, simplified: no GPU bin cap branch, no random
    search-group subsampling — the search set is all compatible groups)."""
    groups: List[List[int]] = []
    marks: List[np.ndarray] = []
    conflict_used: List[int] = []
    group_bins: List[int] = []
    for fidx in order:
        nz = nonzero_masks[fidx]
        cnt = int(nz.sum())
        placed = False
        for gid in range(len(groups)):
            if group_bins[gid] + num_bins[fidx] + 1 > MAX_GROUP_BINS:
                continue
            rest = max_conflict_cnt - conflict_used[gid]
            if rest < 0:
                continue
            conflict = int((marks[gid] & nz).sum())
            if conflict <= rest and conflict <= cnt // 2:
                groups[gid].append(fidx)
                marks[gid] |= nz
                conflict_used[gid] += conflict
                group_bins[gid] += num_bins[fidx]
                placed = True
                break
        if not placed:
            groups.append([fidx])
            marks.append(nz.copy())
            conflict_used.append(0)
            group_bins.append(num_bins[fidx] + 1)
    return groups


def nibble_slot_partition(widths):
    """(wide, pairs, leftover): the shared 4-bit slot-assignment policy.

    Groups whose bin count fits 4 bits pair up two per byte slot; the
    rest keep full byte slots. ONE implementation feeds both storage
    packers — BinnedDataset.device_pack_plan (HBM v1 storage) and the
    persist payload plan (ops/grow_persist._payload_plan) — so the
    pairing threshold/order cannot drift between them.
    """
    G = len(widths)
    narrow = [g for g in range(G) if widths[g] <= 16]
    wide = [g for g in range(G) if widths[g] > 16]
    pairs = [(narrow[i], narrow[i + 1])
             for i in range(0, len(narrow) - 1, 2)]
    leftover = narrow[-1] if len(narrow) % 2 else None
    return wide, pairs, leftover


class BinnedDataset:
    """The binned training matrix + per-feature metadata (dataset.h:333)."""

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.bin_mappers: List[BinMapper] = []        # per original feature
        self.used_features: List[int] = []            # original idx, non-trivial
        self.inner_of: Dict[int, int] = {}            # original -> inner
        self.groups: List[List[int]] = []             # inner feature ids
        self.metadata: Optional[Metadata] = None
        # host arrays describing the device layout
        self.binned: Optional[np.ndarray] = None      # [N, G] narrow dtype
        self.group_offset: Optional[np.ndarray] = None  # [G] i32
        self.group_of: Optional[np.ndarray] = None    # [F_inner] i32
        self.bin_start: Optional[np.ndarray] = None   # [F_inner] i32 global
        self.bin_end: Optional[np.ndarray] = None
        self.most_freq_bin: Optional[np.ndarray] = None
        self.default_bin: Optional[np.ndarray] = None
        self.missing_type_arr: Optional[np.ndarray] = None
        self.is_categorical: Optional[np.ndarray] = None
        self.monotone: Optional[np.ndarray] = None
        self.penalty: Optional[np.ndarray] = None
        self.needs_fix: Optional[np.ndarray] = None   # bundled features
        self.total_bins: int = 0
        # multi-value (ELL row-sparse) storage, the MultiValBin/SparseBin
        # analog — populated instead of `binned` when the dense [N, G]
        # matrix would dwarf the per-row non-default entries
        # (ref src/io/multi_val_sparse_bin.hpp, sparse_bin.hpp)
        self.is_multival: bool = False
        self.ell_grp: Optional[np.ndarray] = None     # [N, K] group ids
        self.ell_bin: Optional[np.ndarray] = None     # [N, K] local bins

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, X, config: Config,
                    categorical_features: Sequence[int] = (),
                    label=None, weight=None, group=None, init_score=None,
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    ) -> "BinnedDataset":
        """Build from an in-memory matrix (reference
        DatasetLoader::CostructFromSampleData, src/io/dataset_loader.cpp:528).

        If `reference` is given (a validation set aligned to a train set),
        its BinMappers and grouping are reused
        (LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:230).
        """
        X = np.ascontiguousarray(X, dtype=np.float64)
        n, nf = X.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = nf
        ds.feature_names = feature_names or ["Column_%d" % i for i in range(nf)]
        ds.metadata = Metadata(n)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weight(weight)
        ds.metadata.set_query(group)
        ds.metadata.set_init_score(init_score)

        if reference is not None:
            ds.bin_mappers = reference.bin_mappers
            ds.used_features = reference.used_features
            ds.inner_of = reference.inner_of
            ds.groups = reference.groups
            ds._finish_layout_like(reference)
            ds._push_matrix(X)
            return ds

        cat_set = set(int(c) for c in categorical_features)
        sample = _sample_data(X, config.bin_construct_sample_cnt,
                              config.data_random_seed)
        with timer.scope("io::FindBinAndGroup", category="io"):
            ds._construct_from_sample(sample, n, config, cat_set)
        with timer.scope("io::PushMatrix(binning)", category="io"):
            ds._push_matrix(X)
        return ds

    def _construct_from_sample(self, sample: np.ndarray, n: int,
                               config: Config, cat_set) -> None:
        """BinMapper construction + EFB grouping + layout from a row sample
        (DatasetLoader::CostructFromSampleData, dataset_loader.cpp:528)."""
        ds = self
        nf = ds.num_total_features
        total_sample = (sample.total if isinstance(sample, SampleCols)
                        else sample.shape[0])
        filter_cnt = max(
            int(config.min_data_in_leaf * total_sample / max(n, 1)), 1)

        forced: Dict[int, List[float]] = _load_forced_bins(
            config.forcedbins_filename, nf)

        def _col(f):
            if isinstance(sample, SampleCols):
                return sample.values[f]
            return sample[:, f]

        mbbf = list(config.max_bin_by_feature)
        if mbbf and len(mbbf) != nf:
            Log.fatal("max_bin_by_feature has %d entries for %d features"
                      % (len(mbbf), nf))
        ds.bin_mappers = []
        for f in range(nf):
            col = _col(f)
            nonzero = col[(np.abs(col) > kZeroThreshold) | np.isnan(col)]
            m = BinMapper()
            m.find_bin(
                nonzero, total_sample,
                int(mbbf[f]) if mbbf else config.max_bin,
                config.min_data_in_bin,
                filter_cnt, pre_filter=bool(config.feature_pre_filter),
                bin_type=BinType.CATEGORICAL if f in cat_set else BinType.NUMERICAL,
                use_missing=config.use_missing,
                zero_as_missing=config.zero_as_missing,
                forced_upper_bounds=forced.get(f, ()))
            ds.bin_mappers.append(m)

        ds.used_features = [f for f in range(nf) if not ds.bin_mappers[f].is_trivial]
        if not ds.used_features:
            Log.warning("There are no meaningful features, as all feature "
                        "values are constant.")
        ds.inner_of = {f: i for i, f in enumerate(ds.used_features)}

        # ---- EFB grouping over inner features -------------------------
        inner_mappers = [ds.bin_mappers[f] for f in ds.used_features]
        n_inner = len(inner_mappers)
        if config.enable_bundle and n_inner > 1:
            nz_masks = []
            for i, f in enumerate(ds.used_features):
                mapper = inner_mappers[i]
                if isinstance(sample, SampleCols):
                    bins = mapper.value_to_bin(sample.values[f])
                    mask = np.zeros(total_sample, bool)
                    mask[sample.rows[f][bins != mapper.most_freq_bin]] = True
                    nz_masks.append(mask)
                else:
                    bins = mapper.value_to_bin(sample[:, f])
                    nz_masks.append(bins != mapper.most_freq_bin)
            order = sorted(range(n_inner),
                           key=lambda i: -int(nz_masks[i].sum()))
            max_conflict = int(total_sample / 10000
                               + config.max_conflict_rate * total_sample)
            groups = _greedy_bundle(
                nz_masks, order, [m.num_bin for m in inner_mappers],
                total_sample, max_conflict)
            ds.groups = groups
        else:
            ds.groups = [[i] for i in range(n_inner)]

        ds._finish_layout(config)

    @classmethod
    def from_sparse(cls, X, config: Config,
                    categorical_features: Sequence[int] = (),
                    label=None, weight=None, group=None, init_score=None,
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    ) -> "BinnedDataset":
        """Streaming CSR ingest: sample -> bin mappers -> chunked binning,
        never materializing the dense [n, features] matrix (the reference
        streams sparse rows through Dataset::PushOneRow the same way,
        src/io/dataset_loader.cpp:714-1004). Host memory is bounded by one
        row chunk (~256 MB dense) + the binned output [n, groups]."""
        import scipy.sparse as sp  # noqa: F401 — import guard: a clear ImportError beats a tocsr AttributeError
        X = X.tocsr()
        X.sort_indices()
        n, nf = X.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = nf
        ds.feature_names = (feature_names
                            or ["Column_%d" % i for i in range(nf)])
        ds.metadata = Metadata(n)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weight(weight)
        ds.metadata.set_query(group)
        ds.metadata.set_init_score(init_score)

        if reference is None:
            cat_set = set(int(c) for c in categorical_features)
            cnt = int(config.bin_construct_sample_cnt)
            if n <= cnt:
                samp = X
                total = n
            else:
                rng = np.random.default_rng(config.data_random_seed)
                idx = rng.choice(n, size=cnt, replace=False)
                idx.sort()
                samp = X[idx]
                total = cnt
            sc = samp.tocsc()
            vals = [sc.data[sc.indptr[f]:sc.indptr[f + 1]].astype(np.float64)
                    for f in range(nf)]
            rows = [sc.indices[sc.indptr[f]:sc.indptr[f + 1]]
                    for f in range(nf)]
            with timer.scope("io::FindBinAndGroup", category="io"):
                ds._construct_from_sample(SampleCols(vals, rows, total),
                                          n, config, cat_set)
        else:
            ds.bin_mappers = reference.bin_mappers
            ds.used_features = reference.used_features
            ds.inner_of = reference.inner_of
            ds.groups = reference.groups
            ds._finish_layout_like(reference)

        with timer.scope("io::PushSparse(binning)", category="io"):
            G = len(ds.groups)
            chunk = max(1024, int(2 ** 25 / max(nf, 1)))
            if ds._choose_multival(config, X):
                # stream into the multi-value layout: host memory is
                # bounded by one dense chunk + the non-default entries
                # (the dense [n, G] matrix is never materialized)
                gd = ds.group_default_bins()
                buf = np.zeros((chunk, G), dtype=ds._bin_dtype())
                coo = []
                for a in range(0, n, chunk):
                    b = min(a + chunk, n)
                    Xc = np.asarray(X[a:b].todense(), dtype=np.float64)
                    ds._bin_rows(Xc, buf[:b - a])
                    coo.append(ds._dense_chunk_to_coo(buf[:b - a], a, gd))
                ds._assemble_ell(
                    coo, n,
                    force=str(getattr(config, "tpu_multival",
                                      "auto")).lower() == "force")
            else:
                binned = np.zeros((n, G), dtype=ds._bin_dtype())
                for a in range(0, n, chunk):
                    b = min(a + chunk, n)
                    Xc = np.asarray(X[a:b].todense(), dtype=np.float64)
                    ds._bin_rows(Xc, binned[a:b])
                ds.binned = binned
        return ds

    def _choose_multival(self, config: Config, X=None) -> bool:
        """Pick the multi-value (ELL) device layout when the dense [N, G]
        matrix would dwarf the per-row non-default entries — the
        reference's MultiValBin decision re-derived for static-shape HBM
        storage (Dataset::TestMultiThreadingMethod / sparse_threshold,
        src/io/dataset.cpp:350-430)."""
        mode = str(getattr(config, "tpu_multival", "auto")).lower()
        if mode in ("off", "false", "0"):
            return False
        if mode == "force":
            return True
        if X is None:
            return False
        G = len(self.groups)
        if G < 64:
            return False
        e_row = X.nnz / max(1, X.shape[0])
        dense_bytes = G * np.dtype(self._bin_dtype()).itemsize
        grp_dt, bin_dt = self._ell_dtypes()
        ell_bytes = ((e_row + 1.0)
                     * (np.dtype(grp_dt).itemsize + np.dtype(bin_dt).itemsize))
        return dense_bytes > 4.0 * ell_bytes

    @classmethod
    def from_matrix_with_mappers(cls, X, config: Config,
                                 mappers, label=None, weight=None,
                                 group=None, init_score=None,
                                 feature_names=None) -> "BinnedDataset":
        """Build a shard dataset from PRE-AGREED BinMappers (distributed
        loading: parallel/distributed.distributed_bin_mappers). EFB is
        off — each feature is its own group — so every rank derives the
        identical layout from the identical mappers and sharded histogram
        psums line up bin-for-bin."""
        X = np.ascontiguousarray(X, dtype=np.float64)
        n, nf = X.shape
        if len(mappers) != nf:
            Log.fatal("%d mappers for %d features" % (len(mappers), nf))
        ds = cls()
        ds.num_data = n
        ds.num_total_features = nf
        ds.feature_names = (list(feature_names) if feature_names
                            else ["Column_%d" % i for i in range(nf)])
        ds.metadata = Metadata(n)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weight(weight)
        ds.metadata.set_query(group)
        ds.metadata.set_init_score(init_score)
        ds.bin_mappers = list(mappers)
        ds.used_features = [f for f in range(nf)
                            if not ds.bin_mappers[f].is_trivial]
        ds.inner_of = {f: i for i, f in enumerate(ds.used_features)}
        ds.groups = [[i] for i in range(len(ds.used_features))]
        ds._finish_layout(config)
        ds._push_matrix(X)
        return ds

    @classmethod
    def from_text_two_round(cls, filename: str, config: Config,
                            categorical_features: Sequence[int] = ()
                            ) -> "BinnedDataset":
        """Two-pass streaming file load (two_round, DatasetLoader::
        LoadFromFile sample-from-file branch, dataset_loader.cpp:168-274):
        pass 1 reservoir-samples rows for binning and collects the small
        metadata columns; pass 2 streams chunks straight into the binned
        matrix — the full float matrix is never materialized."""
        from .loader import _sidecar, iter_text_chunks
        rng = np.random.default_rng(config.data_random_seed)
        cap = int(config.bin_construct_sample_cnt)
        sample_rows: List[np.ndarray] = []
        seen = 0
        labels, weights, groups_col = [], [], []
        names = None
        group_is_sizes = False
        full_X = None
        for chunk in iter_text_chunks(filename, config):
            if names is None:
                names = chunk.feature_names
            if getattr(chunk, "group_is_sizes", False):
                # LibSVM fallback: one full chunk — keep it so pass 2 does
                # not re-parse the file
                group_is_sizes = True
                full_X = chunk.X
            labels.append(chunk.label)
            if chunk.weight is not None:
                weights.append(chunk.weight)
            if chunk.group is not None:
                groups_col.append(chunk.group)
            m = chunk.X.shape[0]
            # chunk-reservoir: keep each row with prob cap/(seen+m) and
            # evict uniformly (approximate reservoir, exact in expectation)
            if seen + m <= cap:
                sample_rows.append(chunk.X)
            else:
                k = max(0, cap - max(seen, 0)) if seen < cap else 0
                take = rng.random(m) < cap / (seen + m)
                take[:k] = True
                sample_rows.append(chunk.X[take])
            seen += m
        n = seen
        sample = np.concatenate(sample_rows) if sample_rows else np.zeros((0, 1))
        if sample.shape[0] > cap:
            sample = sample[rng.choice(sample.shape[0], cap, replace=False)]

        ds = cls()
        ds.num_data = n
        ds.num_total_features = sample.shape[1]
        ds.feature_names = names or ["Column_%d" % i
                                     for i in range(ds.num_total_features)]
        ds.metadata = Metadata(n)
        ds.metadata.set_label(np.concatenate(labels) if labels else
                              np.zeros(n, np.float32))
        if weights:
            ds.metadata.set_weight(np.concatenate(weights))
        if groups_col:
            gids = np.concatenate(groups_col)
            if group_is_sizes:    # LibSVM fallback already returns sizes
                ds.metadata.set_query(gids)
            else:
                change = np.nonzero(np.diff(gids) != 0)[0]
                bounds = np.concatenate([[0], change + 1, [len(gids)]])
                ds.metadata.set_query(np.diff(bounds))
        else:
            # sidecar files, same as the one-round loader
            g_sc = _sidecar(filename, ".query", None)
            if g_sc is not None:
                ds.metadata.set_query(g_sc)
        if not weights:
            w_sc = _sidecar(filename, ".weight", None)
            if w_sc is not None:
                ds.metadata.set_weight(w_sc)
        from .loader import load_init_sidecar
        i_sc = load_init_sidecar(filename)
        if i_sc is not None:
            ds.metadata.set_init_score(i_sc)
        ds._construct_from_sample(sample, n, config,
                                  set(int(c) for c in categorical_features))

        G = len(ds.groups)
        binned = np.zeros((n, G), dtype=ds._bin_dtype())
        if full_X is not None:
            ds._bin_rows(full_X, binned)
        else:
            row = 0
            for chunk in iter_text_chunks(filename, config):
                m = chunk.X.shape[0]
                ds._bin_rows(chunk.X, binned[row:row + m])
                row += m
        ds.binned = binned
        return ds

    # ------------------------------------------------------------------
    def _finish_layout(self, config: Config) -> None:
        inner_mappers = [self.bin_mappers[f] for f in self.used_features]
        n_inner = len(inner_mappers)
        G = len(self.groups)
        self.group_of = np.zeros(n_inner, dtype=np.int32)
        self.bin_start = np.zeros(n_inner, dtype=np.int32)
        self.bin_end = np.zeros(n_inner, dtype=np.int32)
        self.needs_fix = np.zeros(n_inner, dtype=bool)
        self.group_offset = np.zeros(G, dtype=np.int32)
        offset = 0
        for gid, feats in enumerate(self.groups):
            self.group_offset[gid] = offset
            multi = len(feats) > 1
            local = 1 if multi else 0    # local bin 0 = group default sentinel
            for i in feats:
                m = inner_mappers[i]
                self.group_of[i] = gid
                self.bin_start[i] = offset + local
                self.bin_end[i] = offset + local + m.num_bin
                self.needs_fix[i] = multi
                local += m.num_bin
            offset += local
        self.total_bins = int(offset)

        self.most_freq_bin = np.array(
            [m.most_freq_bin for m in inner_mappers], dtype=np.int32)
        self.default_bin = np.array(
            [m.default_bin for m in inner_mappers], dtype=np.int32)
        self.missing_type_arr = np.array(
            [m.missing_type for m in inner_mappers], dtype=np.int32)
        self.is_categorical = np.array(
            [m.is_categorical for m in inner_mappers], dtype=bool)
        mono = np.zeros(n_inner, dtype=np.int32)
        if config.monotone_constraints:
            mc = config.monotone_constraints
            for i, f in enumerate(self.used_features):
                if f < len(mc):
                    mono[i] = mc[f]
        self.monotone = mono
        pen = np.ones(n_inner, dtype=np.float64)
        if config.feature_contri:
            fc = config.feature_contri
            for i, f in enumerate(self.used_features):
                if f < len(fc):
                    pen[i] = fc[f]
        self.penalty = pen

    def _finish_layout_like(self, ref: "BinnedDataset") -> None:
        for attr in ("group_of", "bin_start", "bin_end", "needs_fix",
                     "group_offset", "total_bins", "most_freq_bin",
                     "default_bin", "missing_type_arr", "is_categorical",
                     "monotone", "penalty"):
            setattr(self, attr, getattr(ref, attr))

    def _bin_dtype(self):
        widths = []
        for feats in self.groups:
            multi = len(feats) > 1
            w = (1 if multi else 0) + sum(
                self.bin_mappers[self.used_features[i]].num_bin for i in feats)
            widths.append(w)
        return np.uint8 if max(widths, default=1) <= 256 else (
            np.uint16 if max(widths) <= 65536 else np.int32)

    def _native_bin_meta(self):
        """Flattened per-feature metadata for the C++ binning kernel
        (native/binrows.cpp); built once and cached."""
        if getattr(self, "_nb_meta", None) is not None:
            return self._nb_meta
        gp = [0]
        cols, nb, mf, mt, cat = [], [], [], [], []
        bptr, bvals = [0], []
        lptr, lvals = [0], []
        for feats in self.groups:
            for i in feats:
                f = self.used_features[i]
                m = self.bin_mappers[f]
                cols.append(f)
                nb.append(m.num_bin)
                mf.append(m.most_freq_bin)
                mt.append(int(m.missing_type))
                cat.append(int(m.is_categorical))
                if m.is_categorical:
                    lvals.append(m.categorical_lut())
                    bvals.append(np.zeros(0))
                else:
                    bvals.append(np.asarray(m.bin_upper_bound, np.float64))
                    lvals.append(np.zeros(0, np.int32))
                bptr.append(bptr[-1] + len(bvals[-1]))
                lptr.append(lptr[-1] + len(lvals[-1]))
            gp.append(len(cols))
        self._nb_meta = dict(
            group_ptr=np.asarray(gp, np.int32),
            feat_col=np.asarray(cols, np.int32),
            feat_numbin=np.asarray(nb, np.int32),
            feat_mostfreq=np.asarray(mf, np.int32),
            feat_missing=np.asarray(mt, np.int32),
            feat_iscat=np.asarray(cat, np.int32),
            bounds_ptr=np.asarray(bptr, np.int64),
            bounds=(np.concatenate(bvals) if bvals
                    else np.zeros(0)).astype(np.float64),
            lut_ptr=np.asarray(lptr, np.int64),
            lut=(np.concatenate(lvals) if lvals
                 else np.zeros(0)).astype(np.int32),
        )
        return self._nb_meta

    def _bin_rows_native(self, X: np.ndarray, out: np.ndarray) -> bool:
        """C++/OpenMP binning (native/binrows.cpp); False -> use numpy."""
        from ..native import load
        import ctypes
        if not out.flags["C_CONTIGUOUS"]:
            return False
        lib = load("binrows", extra_flags=("-fopenmp",))
        if lib is None:
            return False
        m = self._native_bin_meta()
        X = np.ascontiguousarray(X, dtype=np.float64)
        p = ctypes.c_void_p

        def arr(a):
            return a.ctypes.data_as(p)
        lib.bin_rows(arr(X), ctypes.c_int64(X.shape[0]),
                     ctypes.c_int64(X.shape[1]),
                     ctypes.c_int32(len(self.groups)),
                     arr(m["group_ptr"]), arr(m["feat_col"]),
                     arr(m["feat_numbin"]), arr(m["feat_mostfreq"]),
                     arr(m["feat_missing"]), arr(m["feat_iscat"]),
                     arr(m["bounds_ptr"]), arr(m["bounds"]),
                     arr(m["lut_ptr"]), arr(m["lut"]),
                     out.ctypes.data_as(p),
                     ctypes.c_int32(out.dtype.itemsize),
                     ctypes.c_int64(out.shape[1]))
        return True

    def _bin_rows(self, X: np.ndarray, out: np.ndarray) -> None:
        """Quantize a row block into group-local bins (writes `out`)."""
        if out.dtype.itemsize in (1, 2, 4) and self._bin_rows_native(X, out):
            return
        n = X.shape[0]
        dtype = out.dtype
        for gid, feats in enumerate(self.groups):
            multi = len(feats) > 1
            if not multi:
                i = feats[0]
                f = self.used_features[i]
                m = self.bin_mappers[f]
                out[:, gid] = m.value_to_bin(X[:, f]).astype(dtype)
            else:
                col = np.zeros(n, dtype=np.int64)
                local = 1
                for i in feats:
                    f = self.used_features[i]
                    m = self.bin_mappers[f]
                    b = m.value_to_bin(X[:, f])
                    nz = b != m.most_freq_bin
                    col[nz] = local + b[nz]
                    local += m.num_bin
                out[:, gid] = col.astype(dtype)

    def _push_matrix(self, X: np.ndarray) -> None:
        """Quantize the full matrix into group-local bins."""
        n = X.shape[0]
        G = len(self.groups)
        binned = np.zeros((n, G), dtype=self._bin_dtype())
        self._bin_rows(X, binned)
        self.binned = binned

    def add_features_from(self, other: "BinnedDataset") -> None:
        """Merge another dataset's features into this one (reference
        Dataset::AddFeaturesFrom, src/io/dataset.cpp:1465). Both must hold
        the same rows; the other's feature groups are appended with their
        global bin ranges shifted past this dataset's."""
        if self.num_data != other.num_data:
            Log.fatal("Cannot add features from a dataset with a different "
                      "number of rows (%d vs %d)"
                      % (other.num_data, self.num_data))
        if self.binned is None or other.binned is None:
            Log.fatal("Both datasets must be constructed before "
                      "add_features_from")
        nf0 = self.num_total_features
        ni0 = len(self.used_features)
        G0 = len(self.groups)
        tb0 = self.total_bins
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.feature_names = (list(self.feature_names)
                              + list(other.feature_names))
        self.used_features = (list(self.used_features)
                              + [nf0 + f for f in other.used_features])
        self.inner_of = {f: i for i, f in enumerate(self.used_features)}
        self.groups = (list(self.groups)
                       + [[ni0 + i for i in g] for g in other.groups])
        self.num_total_features += other.num_total_features
        self.group_of = np.concatenate([self.group_of,
                                        other.group_of + G0])
        self.bin_start = np.concatenate([self.bin_start,
                                         other.bin_start + tb0])
        self.bin_end = np.concatenate([self.bin_end, other.bin_end + tb0])
        self.needs_fix = np.concatenate([self.needs_fix, other.needs_fix])
        self.group_offset = np.concatenate([self.group_offset,
                                            other.group_offset + tb0])
        self.total_bins += other.total_bins
        for attr in ("most_freq_bin", "default_bin", "missing_type_arr",
                     "is_categorical", "monotone", "penalty"):
            setattr(self, attr, np.concatenate([getattr(self, attr),
                                                getattr(other, attr)]))
        dt = np.promote_types(self.binned.dtype, other.binned.dtype)
        self.binned = np.concatenate(
            [self.binned.astype(dt, copy=False),
             other.binned.astype(dt, copy=False)], axis=1)
        # compiled programs are shaped by the old layout
        if hasattr(self, "_scan_cache"):
            self._scan_cache = {}
        if hasattr(self, "_mm_scan_cache"):
            self._mm_scan_cache = {}
        if hasattr(self, "_device_layout_cache"):
            self._device_layout_cache = {}
        self._group_default_cache = None

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        return len(self.used_features)

    @property
    def has_bundles(self) -> bool:
        return bool(self.needs_fix is not None and self.needs_fix.any())

    def group_widths(self) -> np.ndarray:
        """[G] total bins per storage group (incl. the bundle sentinel) —
        the geometry the storage pack plans (device_pack_plan here, the
        persist payload plan in ops/grow_persist) key off."""
        return np.diff(np.append(np.asarray(self.group_offset, np.int64),
                                 int(self.total_bins)))

    def real_threshold(self, inner_feature: int, bin_threshold: int) -> float:
        """Local bin -> model-text threshold value (Tree uses upper bounds)."""
        f = self.used_features[inner_feature]
        return self.bin_mappers[f].bin_to_value(int(bin_threshold))

    # -- binary cache (reference Dataset::SaveBinaryFile, dataset.cpp:890,
    # and DatasetLoader::LoadFromBinFile / CheckCanLoadFromBin,
    # dataset_loader.cpp:179-274). Format: npz with a versioned magic — the
    # semantics match (skip text parsing + FindBin entirely on reload), the
    # encoding is numpy-native instead of the reference's hand-rolled blob.
    BINARY_MAGIC = "lightgbm_tpu.dataset.v1"

    def save_binary(self, path: str) -> None:
        import json
        meta = self.metadata
        arrays = {
            "magic": np.frombuffer(self.BINARY_MAGIC.encode(), np.uint8),
            "group_offset": self.group_offset,
            "group_of": self.group_of,
            "bin_start": self.bin_start,
            "bin_end": self.bin_end,
            "needs_fix": self.needs_fix,
            "most_freq_bin": self.most_freq_bin,
            "default_bin": self.default_bin,
            "missing_type_arr": self.missing_type_arr,
            "is_categorical": self.is_categorical,
            "monotone": self.monotone,
            "penalty": self.penalty,
            "used_features": np.asarray(self.used_features, np.int32),
            "total_bins": np.asarray([self.total_bins], np.int64),
            "num_total_features": np.asarray([self.num_total_features],
                                             np.int64),
            "structure": np.frombuffer(json.dumps({
                "groups": [list(map(int, g)) for g in self.groups],
                "feature_names": list(self.feature_names),
                "mappers": [m.to_state() for m in self.bin_mappers],
            }).encode(), np.uint8),
        }
        if self.is_multival:
            arrays["ell_grp"] = self.ell_grp
            arrays["ell_bin"] = self.ell_bin
        else:
            arrays["binned"] = self.binned
        if meta is not None:
            for k in ("label", "weight", "query_boundaries", "init_score"):
                v = getattr(meta, k)
                if v is not None:
                    arrays["meta_" + k] = v
        with open(path, "wb") as f:
            np.savez_compressed(f, **arrays)
        Log.info("Saved binary dataset to %s" % path)

    @staticmethod
    def is_binary_file(path: str) -> bool:
        try:
            with open(path, "rb") as f:
                head = f.read(4)
            if head[:2] != b"PK":
                return False
            with np.load(path) as z:
                magic = bytes(z["magic"]).decode()
            return magic == BinnedDataset.BINARY_MAGIC
        except Exception:
            return False

    def layout_matches(self, other: "BinnedDataset") -> bool:
        """True when both datasets share the exact binning layout (bin
        boundaries, grouping, feature set) — i.e. a binary cache of a
        reference-aligned validation set is still valid against this
        reference."""
        if (self.total_bins != other.total_bins
                or self.used_features != other.used_features
                or self.groups != other.groups
                or self.num_total_features != other.num_total_features):
            return False

        import json

        def norm(state):
            return json.dumps(state, sort_keys=True, default=str)
        return all(norm(a.to_state()) == norm(b.to_state())
                   for a, b in zip(self.bin_mappers, other.bin_mappers))

    @classmethod
    def from_binary(cls, path: str) -> "BinnedDataset":
        import json
        from .bin_mapper import BinMapper
        ds = cls()
        with np.load(path) as z:
            magic = bytes(z["magic"]).decode()
            if magic != cls.BINARY_MAGIC:
                Log.fatal("%s is not a lightgbm_tpu binary dataset" % path)
            struct = json.loads(bytes(z["structure"]).decode())
            if "ell_grp" in z.files:
                ds.ell_grp = z["ell_grp"]
                ds.ell_bin = z["ell_bin"]
                ds.is_multival = True
            else:
                ds.binned = z["binned"]
            ds.group_offset = z["group_offset"]
            ds.group_of = z["group_of"]
            ds.bin_start = z["bin_start"]
            ds.bin_end = z["bin_end"]
            ds.needs_fix = z["needs_fix"]
            ds.most_freq_bin = z["most_freq_bin"]
            ds.default_bin = z["default_bin"]
            ds.missing_type_arr = z["missing_type_arr"]
            ds.is_categorical = z["is_categorical"]
            ds.monotone = z["monotone"]
            ds.penalty = z["penalty"]
            ds.used_features = [int(x) for x in z["used_features"]]
            ds.total_bins = int(z["total_bins"][0])
            ds.num_total_features = int(z["num_total_features"][0])
            meta_arrays = {k[5:]: z[k] for k in z.files
                           if k.startswith("meta_")}
        ds.groups = [list(g) for g in struct["groups"]]
        ds.feature_names = list(struct["feature_names"])
        ds.bin_mappers = [BinMapper.from_state(d) for d in struct["mappers"]]
        ds.inner_of = {f: i for i, f in enumerate(ds.used_features)}
        ds.num_data = int((ds.ell_grp if ds.is_multival
                           else ds.binned).shape[0])
        ds.metadata = Metadata(ds.num_data)
        for k, v in meta_arrays.items():
            setattr(ds.metadata, k, v)
        Log.info("Loaded binary dataset from %s (%d rows, %d features)"
                 % (path, ds.num_data, ds.num_total_features))
        return ds

    # ------------------------------------------------------------------
    def fix_info(self):
        """FixInfo arrays for features whose histogram omits a bin and
        needs reconstruction from leaf totals (ops.split.fix_histogram).
        Dense layout: only EFB-bundled features (their most_freq rows sit
        in the group sentinel). Multi-value layout: EVERY feature — each
        group's default bin is not materialized (the reference's
        multi-val histograms have the same contract,
        src/io/dataset.cpp:1198 + FixHistogram:1410)."""
        import jax.numpy as jnp
        from ..ops.grow import FixInfo
        if self.is_multival:
            idx = np.arange(self.num_features)
        else:
            idx = np.nonzero(self.needs_fix)[0]
        return FixInfo(
            mf_global=jnp.asarray((self.bin_start[idx]
                                   + self.most_freq_bin[idx]).astype(np.int32)),
            start=jnp.asarray(self.bin_start[idx]),
            end=jnp.asarray(self.bin_end[idx]),
        )

    # -- multi-value (ELL row-sparse) layout ---------------------------
    def group_default_bins(self) -> np.ndarray:
        """[G] bin omitted from multi-value storage per group: the single
        feature's most_freq bin, or the 0 sentinel for EFB bundles.
        Cached — Tree.predict_leaf_binned asks once per leaf level."""
        cached = getattr(self, "_group_default_cache", None)
        if cached is not None and len(cached) == len(self.groups):
            return cached
        G = len(self.groups)
        out = np.zeros(G, dtype=np.int32)
        for g, feats in enumerate(self.groups):
            if len(feats) == 1:
                out[g] = int(self.most_freq_bin[feats[0]])
        self._group_default_cache = out
        return out

    def _ell_dtypes(self):
        G = len(self.groups)
        widths = self.group_widths()
        grp_dt = np.uint16 if G < 0xFFFF else np.int32
        bin_dt = (np.uint8 if (len(widths) == 0 or widths.max() <= 0xFF)
                  else (np.uint16 if widths.max() <= 0xFFFF else np.int32))
        return grp_dt, bin_dt

    def _assemble_ell(self, coo_chunks, n: int, force: bool = False) -> bool:
        """COO chunk list [(row_global, grp, bin)] -> padded [N, K] ELL
        arrays (pad entry: grp = G); chunks must cover disjoint contiguous
        row ranges (both callers chunk by rows). Sets is_multival and
        returns True — unless the padded width K (set by the DENSEST row,
        not the mean the chooser estimated from) would make ELL as large
        as the dense matrix, in which case it densifies instead and
        returns False. `force` (tpu_multival=force) skips that guard."""
        G = len(self.groups)
        grp_dt, bin_dt = self._ell_dtypes()
        counts = np.zeros(n, dtype=np.int64)
        for rows, _, _ in coo_chunks:
            np.add.at(counts, rows, 1)
        K = max(1, int(counts.max()) if n else 1)
        entry_bytes = np.dtype(grp_dt).itemsize + np.dtype(bin_dt).itemsize
        if (not force
                and K * entry_bytes
                >= G * np.dtype(self._bin_dtype()).itemsize):
            Log.warning("multi-value layout abandoned: one row holds %d "
                        "non-default entries, padding every row that wide "
                        "would exceed the dense [N, %d] matrix" % (K, G))
            self._densify_from_coo(coo_chunks, n)
            return False
        self.ell_grp = np.full((n, K), G, dtype=grp_dt)
        self.ell_bin = np.zeros((n, K), dtype=bin_dt)
        for rows, grp, bn in coo_chunks:
            # entries arrive row-sorted; each entry's slot is its
            # occurrence index within its row
            first = np.ones(len(rows), dtype=bool)
            first[1:] = rows[1:] != rows[:-1]
            pos = np.arange(len(rows)) - np.maximum.accumulate(
                np.where(first, np.arange(len(rows)), 0))
            self.ell_grp[rows, pos] = grp.astype(grp_dt)
            self.ell_bin[rows, pos] = bn.astype(bin_dt)
        self.is_multival = True
        self.binned = None
        return True

    def _densify_from_coo(self, coo_chunks, n: int) -> None:
        """Rebuild the dense [N, G] matrix from non-default COO entries
        plus per-group defaults (the _assemble_ell fallback)."""
        gd = self.group_default_bins()
        binned = np.tile(gd.astype(self._bin_dtype()), (n, 1))
        for rows, grp, bn in coo_chunks:
            binned[rows, grp] = bn.astype(self._bin_dtype())
        self.binned = binned
        self.is_multival = False

    def _dense_chunk_to_coo(self, binned_chunk: np.ndarray, row0: int,
                            group_default: np.ndarray):
        """Non-default entries of one dense binned chunk as row-sorted
        (global row, group, bin) COO arrays."""
        rr, gg = np.nonzero(binned_chunk != group_default[None, :])
        return (rr.astype(np.int64) + row0, gg.astype(np.int32),
                binned_chunk[rr, gg].astype(np.int32))

    def to_multival(self) -> None:
        """Convert a dense-binned dataset to the multi-value layout in
        place (tpu_multival=force; tests and post-hoc conversion)."""
        if self.is_multival or self.binned is None:
            return
        gd = self.group_default_bins()
        chunks = []
        step = max(1, int(2 ** 24 / max(1, len(self.groups))))
        for a in range(0, self.num_data, step):
            chunks.append(self._dense_chunk_to_coo(
                self.binned[a:a + step], a, gd))
        self._assemble_ell(chunks, self.num_data, force=True)

    def host_group_bins(self, rows: np.ndarray, g: np.ndarray) -> np.ndarray:
        """Per-row group-local bin for (row, group) pairs from either
        layout — the host-side analog of ops.grow._multival_col, used by
        Tree.predict_leaf_binned."""
        if not self.is_multival:
            return self.binned[rows, g].astype(np.int64)
        eg = self.ell_grp[rows].astype(np.int64)         # [R, K]
        eb = self.ell_bin[rows].astype(np.int64)
        match = eg == np.asarray(g)[:, None]
        found = match.any(axis=1)
        raw = np.where(match, eb, 0).sum(axis=1)
        gd = self.group_default_bins()
        return np.where(found, raw, gd[np.asarray(g)])

    def device_pack_plan(self, config: Config):
        """Nibble-packing plan for HBM storage (the Dense4bitsBin analog,
        src/io/dense_nbits_bin.hpp): pairs of logical groups whose width
        fits 4 bits share one storage byte. Returns None when packing is
        off or fewer than 2 groups qualify; else (storage_of [G_l],
        shift [G_l], n_storage, unpack_mask [G_l])."""
        if not bool(config.tpu_4bit_packing) or self.binned is None:
            return None
        G = len(self.groups)
        widths = self.group_widths()
        wide, pairs, leftover = nibble_slot_partition(widths)
        if G - len(wide) < 2:       # fewer than 2 narrow groups: no pairs
            return None
        storage_of = np.zeros(G, dtype=np.int32)
        shift = np.zeros(G, dtype=np.int32)
        sc = 0
        for g in wide:
            storage_of[g] = sc
            sc += 1
        for a, b in pairs:          # two narrow groups per storage column
            storage_of[a] = sc
            storage_of[b] = sc
            shift[b] = 4
            sc += 1
        if leftover is not None:
            storage_of[leftover] = sc
            sc += 1
        # any narrow group's values fit in 4 bits, so &15 is safe even for
        # an unpaired trailing one; wide groups pass through unmasked
        mask = np.where(widths <= 16, 15, 0x7FFFFFFF).astype(np.int32)
        return storage_of, shift, sc, mask

    def to_device(self, config: Config):
        """Produce (DataLayout, FeatureMeta) jnp structures. Sets
        self.device_packed for the learner's GrowConfig.

        Cached per (tpu_multival, tpu_4bit_packing) — the only config
        knobs the layout depends on — so B boosters sweeping over one
        Dataset share a single HBM-resident copy of the binned matrix
        instead of re-uploading it per member."""
        key = (str(getattr(config, "tpu_multival", "auto")).lower(),
               bool(config.tpu_4bit_packing))
        cache = getattr(self, "_device_layout_cache", None)
        if cache is None:
            cache = self._device_layout_cache = {}
        hit = cache.get(key)
        if hit is not None:
            self.device_packed = hit[2]
            return hit[0], hit[1]
        layout, meta = self._build_device_layout(config)
        cache[key] = (layout, meta, self.device_packed)
        return layout, meta

    def _build_device_layout(self, config: Config):
        import jax.numpy as jnp
        from ..ops.grow import DataLayout
        from ..ops.split import FeatureMeta
        # sentinel bins (bundled group bin 0) belong to no feature; they are
        # assigned feature 0, which is safe: they lie outside every feature's
        # [bin_start, bin_end) so the scan's range masks exclude them.
        owner = np.full(self.total_bins, -1, dtype=np.int32)
        for i in range(self.num_features):
            owner[self.bin_start[i]:self.bin_end[i]] = i
        feat_id = np.where(owner < 0, 0, owner).astype(np.int32)

        if (not self.is_multival and self.binned is not None
                and str(getattr(config, "tpu_multival", "auto")).lower()
                == "force"):
            self.to_multival()
        if self.is_multival:
            self.device_packed = False
            layout = DataLayout(
                # placeholder dense matrix: the multival grower never
                # reads it, but downstream sharding specs expect 2D
                bins=jnp.zeros((self.num_data, 1), jnp.uint8),
                group_offset=jnp.asarray(self.group_offset),
                group_of=jnp.asarray(self.group_of),
                most_freq_bin=jnp.asarray(self.most_freq_bin),
                ell_grp=jnp.asarray(self.ell_grp),
                ell_bin=jnp.asarray(self.ell_bin),
                group_default=jnp.asarray(self.group_default_bins()),
            )
            return layout, self._feature_meta(feat_id)
        plan = self.device_pack_plan(config)
        self.device_packed = plan is not None
        if plan is not None:
            storage_of, shift, n_storage, mask = plan
            storage = np.zeros((self.num_data, n_storage),
                               dtype=self.binned.dtype)
            for g in range(len(self.groups)):
                np.bitwise_or(
                    storage[:, storage_of[g]],
                    (self.binned[:, g].astype(np.int64)
                     << int(shift[g])).astype(self.binned.dtype),
                    out=storage[:, storage_of[g]])
            layout = DataLayout(
                bins=jnp.asarray(storage),
                group_offset=jnp.asarray(self.group_offset),
                group_of=jnp.asarray(self.group_of),
                most_freq_bin=jnp.asarray(self.most_freq_bin),
                unpack_col=jnp.asarray(storage_of),
                unpack_shift=jnp.asarray(shift),
                unpack_mask=jnp.asarray(mask),
            )
        else:
            layout = DataLayout(
                bins=jnp.asarray(self.binned),
                group_offset=jnp.asarray(self.group_offset),
                group_of=jnp.asarray(self.group_of),
                most_freq_bin=jnp.asarray(self.most_freq_bin),
            )
        return layout, self._feature_meta(feat_id)

    def _feature_meta(self, feat_id):
        import jax.numpy as jnp
        from ..ops.split import FeatureMeta
        return FeatureMeta(
            feat_id=jnp.asarray(feat_id),
            bin_start=jnp.asarray(self.bin_start),
            bin_end=jnp.asarray(self.bin_end),
            missing_type=jnp.asarray(self.missing_type_arr),
            default_bin=jnp.asarray(self.default_bin),
            monotone=jnp.asarray(self.monotone),
            is_categorical=jnp.asarray(self.is_categorical),
            penalty=jnp.asarray(self.penalty),
        )


def _load_forced_bins(filename: str, num_features: int) -> Dict[int, List[float]]:
    """forcedbins_filename JSON: [{"feature": i, "bin_upper_bound": [...]}]."""
    if not filename:
        return {}
    import json
    with open(filename) as fh:
        spec = json.load(fh)
    out: Dict[int, List[float]] = {}
    for entry in spec:
        out[int(entry["feature"])] = [float(x) for x in entry["bin_upper_bound"]]
    return out
