"""Text-file dataset loading: CSV / TSV / LibSVM with column resolution.

TPU-native rebuild of the reference parser + loader front-end
(src/io/parser.{hpp,cpp}: CSVParser :18, TSVParser :55, LibSVMParser :91,
format auto-detection :200-216; DatasetLoader::SetHeader column resolution,
src/io/dataset_loader.cpp:31-160). Parsing is vectorized numpy (np.loadtxt-
style) on host; a C fast path can slot in behind the same interface.

Column spec syntax follows the reference: an integer index, or `name:<col>`
when the file has a header (label_column/weight_column/group_column/
ignore_column, config.h).
"""
from __future__ import annotations

import io
import os
from typing import List, Optional, Tuple

import numpy as np

from ..utils.log import Log

NAME_PREFIX = "name:"


def _detect_format(sample_lines: List[str]) -> str:
    """LibSVM when tokens contain ':', else TSV on tabs, else CSV
    (reference parser.cpp:120-216 heuristic, simplified)."""
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        toks = line.replace("\t", " ").replace(",", " ").split()
        has_colon = any(":" in t for t in toks[1:])
        if has_colon:
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
        return "tsv" if len(toks) > 1 else "csv"
    Log.fatal("Unknown format of training data")


def _resolve_column(spec: str, header: Optional[List[str]], what: str) -> int:
    """Column spec -> index; -1 when unset."""
    if not spec:
        return -1
    if spec.startswith(NAME_PREFIX):
        name = spec[len(NAME_PREFIX):]
        if header is None:
            Log.fatal("Cannot use column name %s without header" % name)
        if name not in header:
            Log.fatal("Could not find %s column %s in data file"
                      % (what, name))
        return header.index(name)
    try:
        return int(spec)
    except ValueError:
        Log.fatal("Cannot parse %s column '%s'" % (what, spec))


class LoadedData:
    def __init__(self, X, label, weight, group, feature_names,
                 init_score=None):
        self.X = X
        self.label = label
        self.weight = weight
        self.group = group
        self.feature_names = feature_names
        self.init_score = init_score


def _apply_sidecars(filename: str, loaded: "LoadedData") -> "LoadedData":
    """Metadata files alongside the data file (reference Metadata::
    LoadQueryBoundaries / LoadWeights / LoadInitialScore read <data>.query,
    <data>.weight, <data>.init; dataset_loader.cpp + metadata.cpp)."""
    group = _sidecar(filename, ".query", None)
    if group is not None:
        loaded.group = group
    weight = _sidecar(filename, ".weight", None)
    if weight is not None:
        loaded.weight = weight
    init = load_init_sidecar(filename)
    if init is not None:
        loaded.init_score = init
    return loaded


def load_init_sidecar(filename: str):
    """<data>.init scores, class-major flat (the reference stores
    init_score_[k * num_data + i], metadata.cpp:425; multi-class files are
    row-major columns on disk). Shared by the one-round and two_round
    loaders. None when the file does not exist."""
    init = _sidecar(filename, ".init", None)
    if init is not None and init.ndim == 2:
        init = init.T.reshape(-1)
    return init


def load_text_file(filename: str, config) -> LoadedData:
    """File -> dense matrix + metadata columns."""
    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist" % filename)
    with open(filename, "r") as f:
        text = f.read()
    lines = text.splitlines()
    if not lines:
        Log.fatal("Data file %s is empty" % filename)

    header: Optional[List[str]] = None
    has_header = bool(config.header)
    first_data_line = 0
    sep = None
    fmt = _detect_format(lines[1 if has_header else 0:][:10])
    sep = {"csv": ",", "tsv": "\t", "libsvm": None}[fmt]
    if has_header:
        header = [t.strip() for t in
                  (lines[0].split(sep) if sep else lines[0].split())]
        first_data_line = 1

    label_idx = 0
    if config.label_column:
        label_idx = _resolve_column(config.label_column, header, "label")
    weight_idx = _resolve_column(config.weight_column, header, "weight")
    group_idx = _resolve_column(config.group_column, header, "group")
    ignore_idx: List[int] = []
    if config.ignore_column:
        if config.ignore_column.startswith(NAME_PREFIX):
            for nm in config.ignore_column[len(NAME_PREFIX):].split(","):
                ignore_idx.append(_resolve_column(NAME_PREFIX + nm, header,
                                                  "ignore"))
        else:
            ignore_idx = [int(x) for x in config.ignore_column.split(",")]

    data_lines = lines[first_data_line:]
    data_lines = [ln for ln in data_lines if ln.strip()]

    if fmt == "libsvm":
        return _apply_sidecars(filename,
                               _parse_libsvm(data_lines, label_idx, header))

    mat = np.genfromtxt(io.StringIO("\n".join(data_lines)), delimiter=sep,
                        dtype=np.float64)
    if mat.ndim == 1:
        mat = mat.reshape(-1, 1)
    ncol = mat.shape[1]
    special = {label_idx} | {weight_idx, group_idx} | set(ignore_idx)
    special.discard(-1)
    feat_cols = [c for c in range(ncol) if c not in special]
    label = mat[:, label_idx] if label_idx >= 0 else np.zeros(len(mat))
    weight = mat[:, weight_idx] if weight_idx >= 0 else None
    group_col = mat[:, group_idx] if group_idx >= 0 else None
    group = None
    if group_col is not None:
        # per-row query ids -> query sizes (metadata.cpp SetQueryId path)
        _, counts = np.unique(group_col, return_counts=True)
        # preserve order of appearance
        change = np.nonzero(np.diff(group_col) != 0)[0]
        bounds = np.concatenate([[0], change + 1, [len(group_col)]])
        group = np.diff(bounds)
    X = mat[:, feat_cols]
    names = ([header[c] for c in feat_cols] if header is not None
             else ["Column_%d" % c for c in feat_cols])
    return _apply_sidecars(
        filename, LoadedData(X, label.astype(np.float32), weight, group,
                             names))


def _sidecar(filename: str, suffix: str, default):
    path = filename + suffix
    if os.path.exists(path):
        return np.loadtxt(path)
    return default


def _parse_libsvm(data_lines: List[str], label_idx: int,
                  header) -> LoadedData:
    """index:value rows -> dense matrix (reference LibSVMParser,
    parser.hpp:91; indices are 0-based like the reference's default)."""
    labels = np.empty(len(data_lines))
    rows: List[Tuple[np.ndarray, np.ndarray]] = []
    max_idx = -1
    for i, line in enumerate(data_lines):
        toks = line.split()
        labels[i] = float(toks[0]) if toks else 0.0
        idxs, vals = [], []
        for t in toks[1:]:
            if ":" not in t:
                continue
            k, v = t.split(":", 1)
            idxs.append(int(k))
            vals.append(float(v))
        ii = np.asarray(idxs, dtype=np.int64)
        vv = np.asarray(vals)
        if len(ii):
            max_idx = max(max_idx, int(ii.max()))
        rows.append((ii, vv))
    nf = max_idx + 1
    X = np.zeros((len(data_lines), max(nf, 1)))
    for i, (ii, vv) in enumerate(rows):
        X[i, ii] = vv
    names = ["Column_%d" % c for c in range(X.shape[1])]
    return LoadedData(X, labels.astype(np.float32), None, None, names)


def iter_text_chunks(filename: str, config, chunk_rows: int = 131072):
    """Stream a CSV/TSV file as LoadedData chunks (two_round loading,
    DatasetLoader::LoadFromFile second-round branch): only `chunk_rows`
    parsed rows are alive at a time. chunk.group carries RAW query ids (the
    caller derives sizes after concatenation so chunk boundaries cannot
    split a query's count). LibSVM falls back to one-round.
    """
    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist" % filename)
    with open(filename, "r") as f:
        head = []
        for _ in range(12):
            ln = f.readline()
            if not ln:
                break
            head.append(ln.rstrip("\n"))
    has_header = bool(config.header)
    fmt = _detect_format(head[1 if has_header else 0:][:10])
    if fmt == "libsvm":
        Log.warning("two_round is not supported for LibSVM input; "
                    "loading in one round")
        loaded = load_text_file(filename, config)
        loaded.group_is_sizes = True   # load_text_file returns query SIZES
        yield loaded
        return
    sep = {"csv": ",", "tsv": "\t"}[fmt]
    header = None
    if has_header:
        header = [t.strip() for t in head[0].split(sep)]

    label_idx = 0
    if config.label_column:
        label_idx = _resolve_column(config.label_column, header, "label")
    weight_idx = _resolve_column(config.weight_column, header, "weight")
    group_idx = _resolve_column(config.group_column, header, "group")
    ignore_idx: List[int] = []
    if config.ignore_column:
        if config.ignore_column.startswith(NAME_PREFIX):
            for nm in config.ignore_column[len(NAME_PREFIX):].split(","):
                ignore_idx.append(_resolve_column(NAME_PREFIX + nm, header,
                                                  "ignore"))
        else:
            ignore_idx = [int(x) for x in config.ignore_column.split(",")]

    with open(filename, "r") as f:
        if has_header:
            f.readline()
        while True:
            lines = []
            for ln in f:
                if ln.strip():
                    lines.append(ln)
                if len(lines) >= chunk_rows:
                    break
            if not lines:
                return
            mat = np.genfromtxt(io.StringIO("".join(lines)), delimiter=sep,
                                dtype=np.float64)
            if mat.ndim == 0:
                mat = mat.reshape(1, 1)
            elif mat.ndim == 1:
                # 1-D from genfromtxt: single column (multi-row) or a
                # single row (multi-column)
                mat = (mat.reshape(-1, 1) if len(lines) > 1
                       else mat.reshape(1, -1))
            ncol = mat.shape[1]
            special = {label_idx} | {weight_idx, group_idx} | set(ignore_idx)
            special.discard(-1)
            feat_cols = [c for c in range(ncol) if c not in special]
            label = (mat[:, label_idx] if label_idx >= 0
                     else np.zeros(len(mat)))
            weight = mat[:, weight_idx] if weight_idx >= 0 else None
            group = mat[:, group_idx] if group_idx >= 0 else None
            names = ([header[c] for c in feat_cols] if header is not None
                     else ["Column_%d" % c for c in feat_cols])
            yield LoadedData(mat[:, feat_cols], label.astype(np.float32),
                             weight, group, names)
