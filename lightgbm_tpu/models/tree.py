"""Learned decision tree: SoA arrays, prediction, LightGBM-format text IO.

TPU-native rebuild of the reference Tree (include/LightGBM/tree.h:25,
src/io/tree.cpp). Construction differs by design: the device grower
(ops/grow.py) returns flat TreeArrays (one split record per step), and
`Tree.from_grower` replays them through the same node-numbering scheme as
Tree::Split (tree.h:430-468: internal node k is created by split k, left
child keeps the split leaf's id, right child is new leaf k+1, encoded as
~leaf). Prediction is vectorized numpy over all rows (the reference walks
row-by-row, tree.h:470-510); model text matches Tree::ToString
(src/io/tree.cpp) field-for-field so LightGBM tooling can read our models.

decision_type byte layout (tree.h:19-23, 218-235): bit0 = categorical,
bit1 = default_left, bits 2-3 = missing type (0 none / 1 zero / 2 nan).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

kCategoricalMask = 1
kDefaultLeftMask = 2
kZeroThreshold = 1e-35


def _to_bitset(values) -> np.ndarray:
    """Common::ConstructBitset: uint32 words, bit v set for each value v."""
    values = np.asarray(values, dtype=np.int64)
    if len(values) == 0:
        return np.zeros(1, dtype=np.uint32)
    nwords = int(values.max()) // 32 + 1
    out = np.zeros(nwords, dtype=np.uint32)
    np.bitwise_or.at(out, values // 32, (np.uint32(1) << (values % 32).astype(np.uint32)))
    return out


def _in_bitset(bits: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Vectorized Common::FindInBitset over an int array."""
    word = vals // 32
    ok = (vals >= 0) & (word < len(bits))
    word_safe = np.clip(word, 0, len(bits) - 1)
    return ok & ((bits[word_safe] >> (vals % 32).astype(np.uint32)) & 1).astype(bool)


def _fmt(x: float) -> str:
    """Double -> shortest round-trip string (reference prints %.17g-ish)."""
    return repr(float(x))


def _fmt_arr(a, fmt=str) -> str:
    return " ".join(fmt(x) for x in a)


class Tree:
    """One boosted tree in reference-compatible SoA form."""

    def __init__(self, max_leaves: int):
        L = max(int(max_leaves), 1)
        self.max_leaves = L
        self.num_leaves = 1
        self.num_cat = 0
        self.shrinkage = 1.0
        # internal nodes [L-1]
        self.split_feature_inner = np.zeros(max(L - 1, 1), dtype=np.int32)
        self.split_feature = np.zeros(max(L - 1, 1), dtype=np.int32)
        self.split_gain = np.zeros(max(L - 1, 1), dtype=np.float64)
        self.threshold_in_bin = np.zeros(max(L - 1, 1), dtype=np.int32)
        self.threshold = np.zeros(max(L - 1, 1), dtype=np.float64)
        self.decision_type = np.zeros(max(L - 1, 1), dtype=np.int8)
        self.left_child = np.zeros(max(L - 1, 1), dtype=np.int32)
        self.right_child = np.zeros(max(L - 1, 1), dtype=np.int32)
        self.internal_value = np.zeros(max(L - 1, 1), dtype=np.float64)
        self.internal_weight = np.zeros(max(L - 1, 1), dtype=np.float64)
        self.internal_count = np.zeros(max(L - 1, 1), dtype=np.int32)
        # leaves [L]
        self.leaf_value = np.zeros(L, dtype=np.float64)
        self.leaf_weight = np.zeros(L, dtype=np.float64)
        self.leaf_count = np.zeros(L, dtype=np.int32)
        self.leaf_parent = np.full(L, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(L, dtype=np.int32)
        # categorical storage
        self.cat_boundaries = [0]
        self.cat_threshold: List[int] = []          # uint32 words (real values)
        self.cat_boundaries_inner = [0]
        self.cat_threshold_inner: List[int] = []    # uint32 words (bins)

    # ------------------------------------------------------------------
    @classmethod
    def from_grower(cls, arrays, dataset, bag_counts: Optional[np.ndarray] = None
                    ) -> "Tree":
        """Build from ops/grow.py TreeArrays (host numpy pytree) + the
        BinnedDataset that maps inner features/bins to real ones.

        Replays Tree::Split semantics (tree.h:430-468): split k of recorded
        leaf `l` creates internal node k; left child = ~l, right = ~(k+1).
        """
        n_leaves = int(arrays.num_leaves)
        t = cls(max(n_leaves, 1))
        t.num_leaves = n_leaves
        for k in range(n_leaves - 1):
            leaf = int(arrays.split_leaf[k])
            parent = t.leaf_parent[leaf]
            if parent >= 0:
                if t.left_child[parent] == ~leaf:
                    t.left_child[parent] = k
                else:
                    t.right_child[parent] = k
            inner_f = int(arrays.split_feature[k])
            real_f = dataset.used_features[inner_f]
            mapper = dataset.bin_mappers[real_f]
            t.split_feature_inner[k] = inner_f
            t.split_feature[k] = real_f
            t.split_gain[k] = float(arrays.gain[k])
            t.left_child[k] = ~leaf
            t.right_child[k] = ~(k + 1)
            t.leaf_parent[leaf] = k
            t.leaf_parent[k + 1] = k
            t.internal_value[k] = float(arrays.internal_value[k])
            t.internal_count[k] = int(arrays.internal_count[k])
            dt = np.int8(0)
            missing_type = int(mapper.missing_type)
            if bool(arrays.is_cat[k]):
                dt |= kCategoricalMask
                bins = np.nonzero(np.asarray(arrays.cat_mask[k]))[0]
                bins = bins[bins < mapper.num_bin]
                cats = np.array([mapper.bin_2_categorical[b] for b in bins],
                                dtype=np.int64)
                cats = cats[cats >= 0]
                inner_bits = _to_bitset(bins)
                real_bits = _to_bitset(cats)
                t.threshold_in_bin[k] = len(t.cat_boundaries_inner) - 1
                t.threshold[k] = float(t.num_cat)
                t.num_cat += 1
                t.cat_boundaries.append(t.cat_boundaries[-1] + len(real_bits))
                t.cat_threshold.extend(int(x) for x in real_bits)
                t.cat_boundaries_inner.append(
                    t.cat_boundaries_inner[-1] + len(inner_bits))
                t.cat_threshold_inner.extend(int(x) for x in inner_bits)
            else:
                if bool(arrays.default_left[k]):
                    dt |= kDefaultLeftMask
                dt |= np.int8(missing_type << 2)
                bin_thr = int(arrays.threshold[k])
                t.threshold_in_bin[k] = bin_thr
                t.threshold[k] = mapper.bin_to_value(bin_thr)
            t.decision_type[k] = dt
        lv = np.asarray(arrays.leaf_value, dtype=np.float64)[:max(n_leaves, 1)]
        t.leaf_value[:len(lv)] = np.where(np.isnan(lv), 0.0, lv)
        t.leaf_count[:n_leaves] = np.asarray(arrays.leaf_count)[:n_leaves]
        t.leaf_weight[:n_leaves] = np.asarray(arrays.leaf_weight)[:n_leaves]
        t._fill_internal_weight_and_depth()
        return t

    def _fill_internal_weight_and_depth(self) -> None:
        """internal_weight = subtree sum-of-hessian (reference stores the
        parent leaf's weight at split time, tree.h:456); leaf_depth via a
        top-down walk. Reconstructed bottom-up: node k's children always
        have index > k or are leaves, so a reverse scan suffices for weight."""
        n = self.num_leaves
        if n <= 1:
            return
        for k in range(n - 2, -1, -1):
            lw = (self.leaf_weight[~self.left_child[k]]
                  if self.left_child[k] < 0
                  else self.internal_weight[self.left_child[k]])
            rw = (self.leaf_weight[~self.right_child[k]]
                  if self.right_child[k] < 0
                  else self.internal_weight[self.right_child[k]])
            self.internal_weight[k] = lw + rw
        self._fill_leaf_depth()

    def _fill_leaf_depth(self) -> None:
        n = self.num_leaves
        if n <= 1:
            return
        depth = np.zeros(n - 1, dtype=np.int32)
        for k in range(n - 1):
            for child in (self.left_child[k], self.right_child[k]):
                if child >= 0:
                    depth[child] = depth[k] + 1
                else:
                    self.leaf_depth[~child] = depth[k] + 1

    # ------------------------------------------------------------------
    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:158-170)."""
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:max(self.num_leaves - 1, 0)] *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """Tree::AddBias (tree.h:172-183)."""
        self.leaf_value[:self.num_leaves] += val
        self.internal_value[:max(self.num_leaves - 1, 0)] += val

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = 0.0 if np.isnan(value) else value

    # ------------------------------------------------------------------
    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Vectorized GetLeaf over raw feature rows [N, F] -> leaf idx [N]."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        # at most num_leaves-1 levels
        for _ in range(self.num_leaves):
            if not active.any():
                break
            nd = node[active]
            fv = X[active, self.split_feature[nd]]
            go_left = self._decision(fv, nd)
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = nxt
            active = node >= 0
        return (~node).astype(np.int32)

    def _decision(self, fval: np.ndarray, node: np.ndarray) -> np.ndarray:
        """Vectorized Tree::Decision (tree.h:244-332)."""
        dt = self.decision_type[node]
        is_cat = (dt & kCategoricalMask) != 0
        missing_type = (dt >> 2) & 3
        out = np.zeros(len(fval), dtype=bool)

        num_m = ~is_cat
        if num_m.any():
            fv = fval[num_m].astype(np.float64)
            mt = missing_type[num_m]
            default_left = (dt[num_m] & kDefaultLeftMask) != 0
            isnan = np.isnan(fv)
            fv = np.where(isnan & (mt != 2), 0.0, fv)
            is_zero = np.abs(fv) <= kZeroThreshold
            go_default = ((mt == 1) & is_zero) | ((mt == 2) & isnan)
            cmp = fv <= self.threshold[node[num_m]]
            out[num_m] = np.where(go_default, default_left, cmp)

        if is_cat.any():
            fv = fval[is_cat].astype(np.float64)
            isnan = np.isnan(fv)
            int_fval = np.where(isnan, 0, fv).astype(np.int64)
            res = np.zeros(int(is_cat.sum()), dtype=bool)
            cat_idx = self.threshold[node[is_cat]].astype(np.int32)
            for ci in np.unique(cat_idx):
                m = cat_idx == ci
                bits = np.asarray(
                    self.cat_threshold[self.cat_boundaries[ci]:
                                       self.cat_boundaries[ci + 1]],
                    dtype=np.uint32)
                res[m] = _in_bitset(bits, int_fval[m])
            # NaN always goes right when missing_type==NaN; negative right
            mt = missing_type[is_cat]
            res = np.where(isnan & (mt == 2), False, res)
            res = np.where(fv < 0, False, res)
            out[is_cat] = res
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.full(X.shape[0], self.leaf_value[0])
        return self.leaf_value[self.predict_leaf(X)]

    # -- C++ codegen (Tree::ToIfElse, src/io/tree.cpp:383-440) ----------
    def to_if_else(self, index: int, predict_leaf_index: bool) -> str:
        """Hard-coded C++ prediction function for this tree — the
        convert_model output. Reproduces the model's Decision semantics
        exactly: NaN->0 unless missing_type==NaN, zero/NaN default
        routing, categorical bitset tests."""
        def cfloat(v):
            v = float(v)
            if np.isinf(v):
                return "INFINITY" if v > 0 else "-INFINITY"
            return repr(v)

        name = "PredictTree%d%s" % (index, "Leaf" if predict_leaf_index
                                    else "")
        buf = ["double %s(const double* arr) {" % name]
        if self.num_leaves <= 1:
            out = "0" if predict_leaf_index else cfloat(self.leaf_value[0])
            buf.append("  return %s;" % out)
            buf.append("}")
            return "\n".join(buf)
        if self.num_cat > 0:
            words = ",".join("%uu" % (w & 0xFFFFFFFF)
                             for w in self.cat_threshold)
            buf.append("  static const unsigned int cat_threshold[] = {%s};"
                       % words)
            # long long: on LLP64 targets plain long is 32-bit and would
            # truncate categories >= 2^31 differently from the
            # Python predictor's int64 semantics
            buf.append("  long long int_fval = 0;")
        buf.append("  double fval = 0.0;")

        def leaf(i):
            if predict_leaf_index:
                return "  return %d;" % i
            return "  return %s;" % cfloat(self.leaf_value[i])

        def node(k, indent):
            pad = "  " * indent
            dt = int(self.decision_type[k])
            f = int(self.split_feature[k])
            lines = ["%sfval = arr[%d];" % (pad, f)]
            if dt & kCategoricalMask:
                # mirrors Tree._decision: NaN acts as category 0 unless
                # missing_type==NaN (-> right); fractional negatives in
                # (-1, 0) go right even though (long) truncates them to 0
                ci = int(self.threshold[k])
                b0, b1 = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                nbits = (b1 - b0) * 32
                mt = (dt >> 2) & 3
                lines.append("%sint_fval = std::isnan(fval) ? 0 "
                             ": (long long)fval;" % pad)
                nan_guard = ("!std::isnan(fval) && " if mt == 2 else "")
                lines.append(
                    "%sif (%s(std::isnan(fval) || fval >= 0.0) && "
                    "int_fval < %d && ((cat_threshold[%d + int_fval / 32]"
                    " >> (int_fval %% 32)) & 1)) {"
                    % (pad, nan_guard, nbits, b0))
            else:
                mt = (dt >> 2) & 3
                default_left = bool(dt & kDefaultLeftMask)
                thr = cfloat(self.threshold[k])
                if mt != 2:
                    lines.append("%sif (std::isnan(fval)) fval = 0.0;" % pad)
                if mt == 1:      # zero -> default direction
                    guard = "std::fabs(fval) <= 1e-35"
                elif mt == 2:    # NaN -> default direction
                    guard = "std::isnan(fval)"
                else:
                    guard = None
                cond = "fval <= %s" % thr
                if guard is not None:
                    cond = ("(%s) || (%s)" % (guard, cond) if default_left
                            else "!(%s) && (%s)" % (guard, cond))
                lines.append("%sif (%s) {" % (pad, cond))
            def emit(child):
                if child < 0:
                    return [pad + "  " + leaf(~child).strip()]
                return node(child, indent + 1)
            lines.extend(emit(int(self.left_child[k])))
            lines.append("%s} else {" % pad)
            lines.extend(emit(int(self.right_child[k])))
            lines.append("%s}" % pad)
            return lines

        buf.extend(node(0, 1))
        buf.append("}")
        return "\n".join(buf)

    # -- SHAP feature contributions ------------------------------------
    def expected_value(self) -> float:
        """Count-weighted mean leaf value (Tree SHAP base value)."""
        nl = self.num_leaves
        cnt = self.leaf_count[:nl].astype(np.float64)
        tot = cnt.sum()
        if tot <= 0:
            return float(self.leaf_value[:nl].mean())
        return float((self.leaf_value[:nl] * cnt).sum() / tot)

    def _decision_matrix(self, X: np.ndarray) -> np.ndarray:
        """[rows, internal_nodes] go-left decisions (the same vectorized
        Tree::Decision used for prediction, evaluated at EVERY node)."""
        n = X.shape[0]
        ni = self.num_leaves - 1
        out = np.zeros((n, ni), dtype=np.uint8)
        for j in range(ni):
            fv = X[:, self.split_feature[j]]
            out[:, j] = self._decision(fv, np.full(n, j, dtype=np.int32))
        return out

    def predict_contrib(self, X: np.ndarray, num_features: int,
                        phi: Optional[np.ndarray] = None) -> np.ndarray:
        """Accumulate per-feature SHAP contributions into phi
        [rows, num_features + 1] (last column = expected value).

        TreeSHAP, the same attribution the reference's PredictContrib
        computes (tree.h:137); topology recursion runs in native C++
        (native/treeshap.cpp) with a pure-Python fallback.
        """
        n = X.shape[0]
        if phi is None:
            phi = np.zeros((n, num_features + 1), dtype=np.float64)
        phi[:, -1] += self.expected_value()
        if self.num_leaves <= 1:
            return phi
        ni = self.num_leaves - 1
        go_left = self._decision_matrix(X)
        node_cover = self.internal_count[:ni].astype(np.float64)
        leaf_cover = self.leaf_count[:self.num_leaves].astype(np.float64)
        max_depth = int(self.leaf_depth[:self.num_leaves].max())
        from .. import native
        lib = native.load("treeshap")
        if lib is not None:
            import ctypes as ct
            f64p = np.ctypeslib.ndpointer(np.float64, flags="C")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
            lib.lgbt_tree_shap.argtypes = [
                ct.c_int, ct.c_int, ct.c_int, ct.c_int,
                i32p, i32p, i32p, f64p, f64p, f64p, u8p, f64p]
            phi_c = np.ascontiguousarray(phi)
            lib.lgbt_tree_shap(
                n, ni, num_features + 1, max_depth,
                np.ascontiguousarray(self.left_child[:ni]),
                np.ascontiguousarray(self.right_child[:ni]),
                np.ascontiguousarray(self.split_feature[:ni]),
                np.ascontiguousarray(node_cover),
                np.ascontiguousarray(leaf_cover),
                np.ascontiguousarray(self.leaf_value[:self.num_leaves]),
                np.ascontiguousarray(go_left), phi_c)
            phi[...] = phi_c
            return phi
        for r in range(n):
            _py_tree_shap(self, go_left[r], node_cover, leaf_cover, phi[r])
        return phi

    # -- binned (inner) prediction: for cached-score updates -----------
    def predict_leaf_binned(self, dataset) -> np.ndarray:
        """Vectorized DecisionInner walk over a BinnedDataset aligned with
        this tree's inner features (reference AddPredictionToScore path)."""
        n = dataset.num_data
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        ds = dataset
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        rows_all = np.arange(n)
        for _ in range(self.num_leaves):
            if not active.any():
                break
            nd = node[active]
            f = self.split_feature_inner[nd]
            g = ds.group_of[f]
            col = (ds.host_group_bins(rows_all[active], g)
                   + ds.group_offset[g])
            in_range = (col >= ds.bin_start[f]) & (col < ds.bin_end[f])
            local_bin = np.where(in_range, col - ds.bin_start[f],
                                 ds.most_freq_bin[f])
            go_left = self._decision_inner(local_bin, nd, ds)
            node[active] = np.where(go_left, self.left_child[nd],
                                    self.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)

    def _decision_inner(self, local_bin, node, ds):
        dt = self.decision_type[node]
        is_cat = (dt & kCategoricalMask) != 0
        missing_type = (dt >> 2) & 3
        f = self.split_feature_inner[node]
        nb = ds.bin_end[f] - ds.bin_start[f]
        default_bin = ds.default_bin[f]
        out = np.zeros(len(local_bin), dtype=bool)
        num_m = ~is_cat
        if num_m.any():
            b = local_bin[num_m]
            mt = missing_type[num_m]
            default_left = (dt[num_m] & kDefaultLeftMask) != 0
            go_default = (((mt == 1) & (b == default_bin[num_m]))
                          | ((mt == 2) & (b == nb[num_m] - 1)))
            cmp = b <= self.threshold_in_bin[node[num_m]]
            out[num_m] = np.where(go_default, default_left, cmp)
        if is_cat.any():
            res = np.zeros(int(is_cat.sum()), dtype=bool)
            cat_idx = self.threshold_in_bin[node[is_cat]]
            bv = local_bin[is_cat]
            for ci in np.unique(cat_idx):
                m = cat_idx == ci
                bits = np.asarray(
                    self.cat_threshold_inner[self.cat_boundaries_inner[ci]:
                                             self.cat_boundaries_inner[ci + 1]],
                    dtype=np.uint32)
                res[m] = _in_bitset(bits, bv[m])
            out[is_cat] = res
        return out

    def predict_binned(self, dataset) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.full(dataset.num_data, self.leaf_value[0])
        return self.leaf_value[self.predict_leaf_binned(dataset)]

    # ------------------------------------------------------------------
    def bind_to_dataset(self, dataset) -> "Tree":
        """Reconstruct inner (binned) decision fields from the real-valued
        ones using a BinnedDataset's BinMappers. Needed for trees parsed
        from model text (threshold_in_bin is not serialized — the reference
        re-binds via Dataset mapping too) before predict_binned works."""
        self.cat_boundaries_inner = [0]
        self.cat_threshold_inner = []
        for k in range(self.num_leaves - 1):
            real_f = int(self.split_feature[k])
            inner = dataset.inner_of.get(real_f, -1)
            is_cat = bool(self.decision_type[k] & kCategoricalMask)
            if inner < 0:
                # feature trivial in this dataset: constant value; route all
                # rows by evaluating the decision on that constant.  The
                # all-left threshold must exceed any bin (b <= thr for every
                # b) and the missing-type bits must be cleared so go_default
                # cannot override the constant routing.
                self.split_feature_inner[k] = 0
                mapper = dataset.bin_mappers[real_f]
                const_val = mapper.min_val
                if is_cat:
                    go_left = False
                else:
                    go_left = const_val <= self.threshold[k]
                self.threshold_in_bin[k] = (1 << 30) if go_left else -1
                self.decision_type[k] &= ~np.int8(3 << 2)   # missing: None
                if is_cat:
                    # clear categorical bit: use numerical constant routing
                    self.decision_type[k] &= ~np.int8(kCategoricalMask)
                continue
            self.split_feature_inner[k] = inner
            mapper = dataset.bin_mappers[real_f]
            if is_cat:
                ci = int(self.threshold[k])
                bits = np.asarray(
                    self.cat_threshold[self.cat_boundaries[ci]:
                                       self.cat_boundaries[ci + 1]],
                    dtype=np.uint32)
                cats = [v for v in range(len(bits) * 32)
                        if bits[v // 32] >> (v % 32) & 1]
                bins = [mapper.categorical_2_bin[c] for c in cats
                        if c in mapper.categorical_2_bin]
                inner_bits = _to_bitset(np.asarray(bins, dtype=np.int64))
                self.threshold_in_bin[k] = len(self.cat_boundaries_inner) - 1
                self.cat_boundaries_inner.append(
                    self.cat_boundaries_inner[-1] + len(inner_bits))
                self.cat_threshold_inner.extend(int(x) for x in inner_bits)
            else:
                self.threshold_in_bin[k] = int(
                    mapper.value_to_bin(np.array([self.threshold[k]]))[0])
        return self

    # ------------------------------------------------------------------
    def expected_value(self) -> float:
        """Weighted mean output (used by SHAP base value)."""
        n = self.num_leaves
        total = float(np.sum(self.leaf_count[:n]))
        if total <= 0:
            return float(self.leaf_value[0])
        return float(np.sum(self.leaf_value[:n] * self.leaf_count[:n]) / total)

    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        return int(self.leaf_depth[:self.num_leaves].max())

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """Tree::ToString (src/io/tree.cpp) — byte-compatible field list."""
        n = self.num_leaves
        ni = max(n - 1, 0)
        buf = []
        buf.append("num_leaves=%d" % n)
        buf.append("num_cat=%d" % self.num_cat)
        buf.append("split_feature=" + _fmt_arr(self.split_feature[:ni]))
        buf.append("split_gain=" + _fmt_arr(self.split_gain[:ni], _fmt_g))
        buf.append("threshold=" + _fmt_arr(self.threshold[:ni], _fmt))
        buf.append("decision_type=" + _fmt_arr(self.decision_type[:ni]))
        buf.append("left_child=" + _fmt_arr(self.left_child[:ni]))
        buf.append("right_child=" + _fmt_arr(self.right_child[:ni]))
        buf.append("leaf_value=" + _fmt_arr(self.leaf_value[:n], _fmt))
        buf.append("leaf_weight=" + _fmt_arr(self.leaf_weight[:n], _fmt))
        buf.append("leaf_count=" + _fmt_arr(self.leaf_count[:n]))
        buf.append("internal_value=" + _fmt_arr(self.internal_value[:ni], _fmt_g))
        buf.append("internal_weight=" + _fmt_arr(self.internal_weight[:ni], _fmt_g))
        buf.append("internal_count=" + _fmt_arr(self.internal_count[:ni]))
        if self.num_cat > 0:
            buf.append("cat_boundaries=" + _fmt_arr(self.cat_boundaries))
            buf.append("cat_threshold=" + _fmt_arr(self.cat_threshold))
        buf.append("shrinkage=%s" % _fmt_g(self.shrinkage))
        buf.append("")
        return "\n".join(buf) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        """Parse a tree block (reference Tree::Tree(const char*, size_t*))."""
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            kv[k] = v
        n = int(kv["num_leaves"])
        t = cls(max(n, 1))
        t.num_leaves = n
        t.num_cat = int(kv.get("num_cat", 0))
        t.shrinkage = float(kv.get("shrinkage", 1.0))

        def parse(key, dtype, size):
            if size <= 0 or key not in kv or not kv[key].strip():
                return np.zeros(max(size, 1), dtype=dtype)
            vals = np.array(kv[key].split(), dtype=np.float64)
            return vals.astype(dtype)

        ni = n - 1
        if ni > 0:
            t.split_feature = parse("split_feature", np.int32, ni)
            t.split_feature_inner = t.split_feature.copy()
            t.split_gain = parse("split_gain", np.float64, ni)
            t.threshold = parse("threshold", np.float64, ni)
            t.threshold_in_bin = np.zeros(ni, dtype=np.int32)
            t.decision_type = parse("decision_type", np.int8, ni)
            t.left_child = parse("left_child", np.int32, ni)
            t.right_child = parse("right_child", np.int32, ni)
            t.internal_value = parse("internal_value", np.float64, ni)
            t.internal_weight = parse("internal_weight", np.float64, ni)
            t.internal_count = parse("internal_count", np.int32, ni)
        t.leaf_value = parse("leaf_value", np.float64, n)[:max(n, 1)]
        if "leaf_weight" in kv:
            t.leaf_weight = parse("leaf_weight", np.float64, n)[:max(n, 1)]
        if "leaf_count" in kv:
            t.leaf_count = parse("leaf_count", np.int32, n)[:max(n, 1)]
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        # leaf_depth is not serialized; recompute (predict_contrib sizes the
        # native TreeSHAP scratch from it)
        t._fill_leaf_depth()
        return t

    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        """Tree::ToJSON (src/io/tree.cpp): nested node dict."""
        out = {
            "num_leaves": self.num_leaves,
            "num_cat": self.num_cat,
            "shrinkage": self.shrinkage,
        }
        if self.num_leaves == 1:
            out["tree_structure"] = {"leaf_value": float(self.leaf_value[0])}
        else:
            out["tree_structure"] = self._node_json(0)
        return out

    def _node_json(self, index: int) -> dict:
        if index >= 0:
            dt = int(self.decision_type[index])
            is_cat = bool(dt & kCategoricalMask)
            node = {
                "split_index": index,
                "split_feature": int(self.split_feature[index]),
                "split_gain": float(self.split_gain[index]),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "internal_value": float(self.internal_value[index]),
                "internal_weight": float(self.internal_weight[index]),
                "internal_count": int(self.internal_count[index]),
            }
            if is_cat:
                ci = int(self.threshold[index])
                bits = np.asarray(
                    self.cat_threshold[self.cat_boundaries[ci]:
                                       self.cat_boundaries[ci + 1]],
                    dtype=np.uint32)
                cats = [int(v) for v in range(len(bits) * 32)
                        if bits[v // 32] >> (v % 32) & 1]
                node["decision_type"] = "=="
                node["threshold"] = "||".join(str(c) for c in cats)
                node["default_left"] = False
            else:
                node["decision_type"] = "<="
                node["threshold"] = float(self.threshold[index])
                node["default_left"] = bool(dt & kDefaultLeftMask)
            node["left_child"] = self._node_json(int(self.left_child[index]))
            node["right_child"] = self._node_json(int(self.right_child[index]))
            return node
        leaf = ~index
        return {
            "leaf_index": leaf,
            "leaf_value": float(self.leaf_value[leaf]),
            "leaf_weight": float(self.leaf_weight[leaf]),
            "leaf_count": int(self.leaf_count[leaf]),
        }


def _fmt_g(x) -> str:
    """%g-style float formatting used for gains/weights."""
    return "%g" % float(x)


# ---------------------------------------------------------------------------
# Pure-Python TreeSHAP fallback (native/treeshap.cpp is the fast path).
# Same recursion (Lundberg et al., Algorithm 2); used when g++ is absent.
# ---------------------------------------------------------------------------

def _py_extend(path, depth, pz, po, fi):
    path.append([fi, pz, po, 1.0 if depth == 0 else 0.0])
    for i in range(depth - 1, -1, -1):
        path[i + 1][3] += po * path[i][3] * (i + 1) / (depth + 1)
        path[i][3] = pz * path[i][3] * (depth - i) / (depth + 1)


def _py_unwind(path, depth, idx):
    po, pz = path[idx][2], path[idx][1]
    nxt = path[depth][3]
    for i in range(depth - 1, -1, -1):
        if po != 0:
            tmp = path[i][3]
            path[i][3] = nxt * (depth + 1) / ((i + 1) * po)
            nxt = tmp - path[i][3] * pz * (depth - i) / (depth + 1)
        else:
            path[i][3] = path[i][3] * (depth + 1) / (pz * (depth - i))
    for i in range(idx, depth):
        path[i][0], path[i][1], path[i][2] = \
            path[i + 1][0], path[i + 1][1], path[i + 1][2]
    path.pop()


def _py_unwound_sum(path, depth, idx):
    po, pz = path[idx][2], path[idx][1]
    total, nxt = 0.0, path[depth][3]
    for i in range(depth - 1, -1, -1):
        if po != 0:
            t = nxt * (depth + 1) / ((i + 1) * po)
            total += t
            nxt = path[i][3] - t * pz * (depth - i) / (depth + 1)
        else:
            total += path[i][3] * (depth + 1) / (pz * (depth - i))
    return total


def _py_tree_shap(tree, go_left_row, node_cover, leaf_cover, phi_row):
    def cover(child):
        return node_cover[child] if child >= 0 else leaf_cover[~child]

    def recurse(node, path, pz, po, pf):
        path = [list(e) for e in path]
        depth = len(path)
        _py_extend(path, depth, pz, po, pf)
        if node < 0:
            v = tree.leaf_value[~node]
            for i in range(1, depth + 1):
                w = _py_unwound_sum(path, depth, i)
                phi_row[path[i][0]] += w * (path[i][2] - path[i][1]) * v
            return
        d = int(tree.split_feature[node])
        hot = int(tree.left_child[node] if go_left_row[node]
                  else tree.right_child[node])
        cold = int(tree.right_child[node] if go_left_row[node]
                   else tree.left_child[node])
        iz = io = 1.0
        for k in range(1, len(path)):
            if path[k][0] == d:
                iz, io = path[k][1], path[k][2]
                _py_unwind(path, len(path) - 1, k)
                break
        cn = node_cover[node]
        recurse(hot, path, iz * cover(hot) / cn, io, d)
        recurse(cold, path, iz * cover(cold) / cn, 0.0, d)

    recurse(0, [], 1.0, 1.0, -1)
