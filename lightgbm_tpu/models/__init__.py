"""lightgbm_tpu.models"""
