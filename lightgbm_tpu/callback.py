"""Training callbacks.

Implements the CallbackEnv protocol of the reference python package
(python-package/lightgbm/callback.py) — same factory names, env fields,
`order`/`before_iteration` attributes and EarlyStopException contract, so
user callbacks written for LightGBM run unchanged — but the machinery here
is class-based: each factory returns a small stateful object whose
`__call__(env)` does the work.
"""
from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional

from .utils.log import Log


class EarlyStopException(Exception):
    """Raised by the early_stopping callback to end training."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


class CallbackEnv(NamedTuple):
    """State handed to every callback once per iteration.

    A NamedTuple like the reference's, so third-party callbacks that
    tuple-unpack or index it positionally keep working.
    """
    model: object
    params: dict
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: list


def _format_eval_result(value, show_stdv: bool = True) -> str:
    """One eval tuple -> 'data's metric: 0.123 [+ 0.01]'.

    Tuples are (data, metric, value, is_higher_better) from train() or the
    5-field (data, metric, mean, is_higher_better, stdv) from cv().
    """
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        base = f"{value[0]}'s {value[1]}: {value[2]:g}"
        return base + (f" + {value[4]:g}" if show_stdv else "")
    raise ValueError("Wrong metric value")


class _EvalLogger:
    """Prints the eval tuples every `period` iterations."""

    def __init__(self, period: int, show_stdv: bool):
        self.order = 10
        self.before_iteration = False
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        if (env.iteration + 1) % self.period:
            return
        line = "\t".join(_format_eval_result(v, self.show_stdv)
                         for v in env.evaluation_result_list)
        Log.info("[%d]\t%s" % (env.iteration + 1, line))


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Log evaluation results every `period` iterations."""
    return _EvalLogger(period, show_stdv)


class _HistoryRecorder:
    """Appends each iteration's eval values into a user-supplied dict of
    {data_name: {eval_name: [values...]}}."""

    def __init__(self, store: Dict):
        self.order = 20
        self.before_iteration = False
        if not isinstance(store, dict):
            raise TypeError("eval_result should be a dictionary")
        store.clear()
        self.store = store

    def __call__(self, env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            data_name, eval_name, value = item[0], item[1], item[2]
            self.store.setdefault(data_name, {}) \
                      .setdefault(eval_name, []).append(value)


def record_evaluation(eval_result: Dict) -> Callable:
    """Record evaluation history into `eval_result`."""
    return _HistoryRecorder(eval_result)


class _ParamScheduler:
    """Re-applies parameters on a schedule before each iteration.

    Values may be lists (indexed by iteration) or callables(iteration).
    Training-control parameters route through GBDT.reset_config (the
    ResetConfig analog, gbdt.cpp:704); structurally-fixed ones (objective,
    metric, binning) warn and are skipped.
    """

    def __init__(self, schedule: Dict):
        self.order = 10
        self.before_iteration = True
        self.schedule = schedule
        self._prev = None   # last applied values (reset only on change)

    def _value_at(self, key, spec, env: CallbackEnv):
        step = env.iteration - env.begin_iteration
        if isinstance(spec, list):
            if len(spec) != env.end_iteration - env.begin_iteration:
                raise ValueError("Length of list %r has to equal to "
                                 "'num_boost_round'" % key)
            return spec[step]
        return spec(step)

    def __call__(self, env: CallbackEnv) -> None:
        updates = {k: self._value_at(k, v, env)
                   for k, v in self.schedule.items()}
        if not updates:
            return
        # apply only the keys whose value CHANGED since the previous
        # iteration (reference _reset_parameter_callback compares per
        # entry) — re-applying an unchanged bagging config would reseed
        # the bag RNG into drawing the identical mask each time, even
        # when some OTHER key (a learning-rate decay) changes every step
        prev = self._prev or {}
        changed = {k: v for k, v in updates.items()
                   if k not in prev or prev[k] != v}
        self._prev = updates
        if not changed:
            return
        inner = getattr(env.model, "_booster", None)
        if inner is not None:
            inner.reset_config(changed)
        env.params.update(changed)


def reset_parameter(**kwargs) -> Callable:
    """Change parameters on a per-iteration schedule."""
    return _ParamScheduler(kwargs)


class _MetricState:
    """Best-so-far tracker for one (dataset, metric) eval stream."""

    __slots__ = ("best_value", "best_iteration", "best_snapshot", "bigger")

    def __init__(self, bigger_is_better: bool):
        self.bigger = bigger_is_better
        self.best_value = float("-inf") if bigger_is_better else float("inf")
        self.best_iteration = 0
        self.best_snapshot = None

    def update(self, value, iteration, snapshot) -> None:
        improved = (value > self.best_value if self.bigger
                    else value < self.best_value)
        if self.best_snapshot is None or improved:
            self.best_value = value
            self.best_iteration = iteration
            self.best_snapshot = snapshot


class _EarlyStopper:
    """Stops training when no tracked metric improves for N rounds."""

    def __init__(self, stopping_rounds: int, first_metric_only: bool,
                 verbose: bool):
        self.order = 30
        self.before_iteration = False
        self.rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.states: Optional[List[_MetricState]] = None
        self.enabled = True
        self.first_metric = ""

    def _setup(self, env: CallbackEnv) -> None:
        boosting = next((env.params[k] for k in
                         ("boosting", "boosting_type", "boost")
                         if k in env.params), None)
        if boosting == "dart":
            self.enabled = False
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if self.verbose:
            Log.info("Training until validation scores don't improve for "
                     "%d rounds" % self.rounds)
        # metric name may carry a 'top-k' prefix: compare the last token
        self.first_metric = env.evaluation_result_list[0][1].split(" ")[-1]
        self.states = [_MetricState(bool(item[3]))
                       for item in env.evaluation_result_list]

    # -- resilience: the best-so-far trackers ride the checkpoint -------
    def state_dict(self) -> Optional[Dict]:
        """JSON-able snapshot of the per-metric best trackers (None until
        the first evaluation); resilience checkpoints carry it so a
        resumed run keeps the same patience clock and rollback point."""
        if self.states is None:
            return None
        return {"first_metric": self.first_metric,
                "states": [{"bigger": s.bigger,
                            "best_value": s.best_value,
                            "best_iteration": s.best_iteration,
                            "best_snapshot": s.best_snapshot}
                           for s in self.states]}

    def load_state_dict(self, snap: Dict) -> None:
        self.first_metric = snap["first_metric"]
        self.states = []
        for sd in snap["states"]:
            st = _MetricState(bool(sd["bigger"]))
            st.best_value = float(sd["best_value"])
            st.best_iteration = int(sd["best_iteration"])
            st.best_snapshot = ([tuple(t) for t in sd["best_snapshot"]]
                                if sd["best_snapshot"] else None)
            self.states.append(st)

    def _stop(self, state: _MetricState, reason: str) -> None:
        if self.verbose:
            Log.info("%s, best iteration is:\n[%d]\t%s" % (
                reason, state.best_iteration + 1,
                "\t".join(_format_eval_result(v)
                          for v in state.best_snapshot)))
        raise EarlyStopException(state.best_iteration, state.best_snapshot)

    def __call__(self, env: CallbackEnv) -> None:
        if self.states is None and self.enabled:
            self._setup(env)
        if not self.enabled:
            return
        results = env.evaluation_result_list
        data_names = {item[0] for item in results}
        is_last = env.iteration == env.end_iteration - 1
        for state, item in zip(self.states, results):
            state.update(item[2], env.iteration, results)
            if self.first_metric_only and \
                    item[1].split(" ")[-1] != self.first_metric:
                continue
            train_only_stream = item[0] == "training" and len(data_names) > 1
            if not train_only_stream and \
                    env.iteration - state.best_iteration >= self.rounds:
                self._stop(state, "Early stopping")
            if is_last:
                self._stop(state, "Did not meet early stopping")


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Stop training when validation metrics stall for `stopping_rounds`."""
    return _EarlyStopper(stopping_rounds, first_metric_only, verbose)
