"""The LGBM_* C API surface, Python side.

TPU-native rebuild of src/c_api.cpp (~70 entry points declared in
include/LightGBM/c_api.h). The reference implements the C API in C++ on
top of its C++ core; here the core is Python/JAX, so the layering inverts:
this module implements every entry point against `basic.Dataset`/`Booster`,
and the thin C ABI layer (native/c_api_shim.cpp) embeds CPython and
forwards each exported LGBM_* symbol here — external C/C++/R/Java hosts
get a genuine `lib_lightgbm`-compatible shared library whose compute runs
on TPU.

Calling convention of this module: pointers arrive as integer addresses
(the shim passes them as uintptr_t); ctypes turns them into typed views.
Out-parameters are written directly through those addresses — caller and
callee share one process. Functions return 0 on success and raise on
error; the shim converts exceptions into -1 + LGBM_GetLastError().

Also usable without the shim: `lightgbm_tpu.c_api` + ctypes-allocated
buffers from Python (see tests/test_c_api.py, the analog of the
reference's tests/c_api_test/test_.py).
"""
from __future__ import annotations

import ctypes
import itertools
import json
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import params_to_config
from .utils.log import LightGBMError, Log

# dtype / predict-type constants (c_api.h:26-48)
C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3

_NP_DTYPE = {
    C_API_DTYPE_FLOAT32: np.float32,
    C_API_DTYPE_FLOAT64: np.float64,
    C_API_DTYPE_INT32: np.int32,
    C_API_DTYPE_INT64: np.int64,
}

_handles: Dict[int, Any] = {}
_next_handle = itertools.count(1)


def _register(obj) -> int:
    h = next(_next_handle)
    _handles[h] = obj
    return h


def _get(handle) -> Any:
    obj = _handles.get(int(handle))
    if obj is None:
        raise LightGBMError("Invalid handle %r" % (handle,))
    return obj


def _view(ptr: int, dtype, count: int) -> np.ndarray:
    """Zero-copy numpy view over a raw address."""
    if count == 0:
        return np.empty(0, dtype=dtype)
    ctype = np.ctypeslib.as_ctypes_type(np.dtype(dtype))
    buf = (ctype * count).from_address(int(ptr))
    return np.ctypeslib.as_array(buf)


def _write_out(ptr: int, value, ctype=ctypes.c_int32) -> None:
    ctype.from_address(int(ptr)).value = value


def _params_dict(parameters) -> Dict[str, Any]:
    """`key=value key2=value2` C-style parameter string -> dict
    (Config::KV2Map / Str2Map, config.h:79)."""
    if parameters is None:
        return {}
    if isinstance(parameters, bytes):
        parameters = parameters.decode("utf-8")
    out: Dict[str, Any] = {}
    for tok in str(parameters).split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


class _CDataset:
    """C-API dataset wrapper: a basic.Dataset plus push-rows state."""

    def __init__(self, ds: Dataset, params: Dict[str, Any]):
        self.ds = ds
        self.params = params
        # streaming (PushRows) state
        self.nrow_total = 0
        self.ncol = 0
        self.pending: Optional[np.ndarray] = None
        self.pushed = 0
        self.reference: Optional[_CDataset] = None

    def construct(self):
        self.ds.construct()
        return self.ds


# ---------------------------------------------------------------------------
# Dataset creation (c_api.h:51-255)
# ---------------------------------------------------------------------------

def LGBM_DatasetCreateFromFile(filename, parameters, reference, out) -> int:
    params = _params_dict(parameters)
    ref = _get(reference).ds if reference else None
    if isinstance(filename, bytes):
        filename = filename.decode("utf-8")
    ds = Dataset(str(filename), params=params, reference=ref,
                 free_raw_data=False)
    ds.construct()
    _write_out(out, _register(_CDataset(ds, params)), ctypes.c_uint64)
    return 0


def _mat_from_ptr(data, data_type, nrow, ncol, is_row_major) -> np.ndarray:
    arr = _view(data, _NP_DTYPE[int(data_type)], int(nrow) * int(ncol))
    if is_row_major:
        return arr.reshape(int(nrow), int(ncol)).astype(np.float64)
    return arr.reshape(int(ncol), int(nrow)).T.astype(np.float64)


def LGBM_DatasetCreateFromMat(data, data_type, nrow, ncol, is_row_major,
                              parameters, reference, out) -> int:
    X = _mat_from_ptr(data, data_type, nrow, ncol, is_row_major)
    params = _params_dict(parameters)
    ref = _get(reference).ds if reference else None
    ds = Dataset(X, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    _write_out(out, _register(_CDataset(ds, params)), ctypes.c_uint64)
    return 0


def LGBM_DatasetCreateFromMats(nmat, data_ptrs, data_type, nrows, ncol,
                               is_row_major, parameters, reference,
                               out) -> int:
    ptrs = _view(data_ptrs, np.uint64, int(nmat))
    rows = _view(nrows, np.int32, int(nmat))
    mats = [_mat_from_ptr(int(ptrs[i]), data_type, int(rows[i]), ncol,
                          is_row_major) for i in range(int(nmat))]
    X = np.concatenate(mats, axis=0) if mats else np.empty((0, int(ncol)))
    params = _params_dict(parameters)
    ref = _get(reference).ds if reference else None
    ds = Dataset(X, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    _write_out(out, _register(_CDataset(ds, params)), ctypes.c_uint64)
    return 0


def _indptr_view(ptr, indptr_type, count):
    dt = {C_API_DTYPE_INT32: np.int32, C_API_DTYPE_INT64: np.int64}[
        int(indptr_type)]
    return _view(ptr, dt, count)


def LGBM_DatasetCreateFromCSR(indptr, indptr_type, indices, data, data_type,
                              nindptr, nelem, num_col, parameters,
                              reference, out) -> int:
    ip = _indptr_view(indptr, indptr_type, int(nindptr))
    idx = _view(indices, np.int32, int(nelem))
    vals = _view(data, _NP_DTYPE[int(data_type)], int(nelem))
    nrow = int(nindptr) - 1
    X = np.zeros((nrow, int(num_col)), dtype=np.float64)
    for r in range(nrow):
        s, e = int(ip[r]), int(ip[r + 1])
        X[r, idx[s:e]] = vals[s:e]
    params = _params_dict(parameters)
    ref = _get(reference).ds if reference else None
    ds = Dataset(X, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    _write_out(out, _register(_CDataset(ds, params)), ctypes.c_uint64)
    return 0


def LGBM_DatasetCreateFromCSC(col_ptr, col_ptr_type, indices, data,
                              data_type, ncol_ptr, nelem, num_row,
                              parameters, reference, out) -> int:
    cp = _indptr_view(col_ptr, col_ptr_type, int(ncol_ptr))
    idx = _view(indices, np.int32, int(nelem))
    vals = _view(data, _NP_DTYPE[int(data_type)], int(nelem))
    ncol = int(ncol_ptr) - 1
    X = np.zeros((int(num_row), ncol), dtype=np.float64)
    for c in range(ncol):
        s, e = int(cp[c]), int(cp[c + 1])
        X[idx[s:e], c] = vals[s:e]
    params = _params_dict(parameters)
    ref = _get(reference).ds if reference else None
    ds = Dataset(X, params=params, reference=ref, free_raw_data=False)
    ds.construct()
    _write_out(out, _register(_CDataset(ds, params)), ctypes.c_uint64)
    return 0


def LGBM_DatasetCreateFromSampledColumn(sample_data, sample_indices, ncol,
                                        num_per_col, num_sample_row,
                                        num_total_row, parameters,
                                        out) -> int:
    """Streaming creation: bin mappers from a column sample, rows pushed
    later via LGBM_DatasetPushRows (c_api.h:68 + :98)."""
    params = _params_dict(parameters)
    ncol = int(ncol)
    counts = _view(num_per_col, np.int32, ncol)
    sample_ptrs = _view(sample_data, np.uint64, ncol)
    idx_ptrs = _view(sample_indices, np.uint64, ncol)
    n_sample = int(num_sample_row)
    sample = np.zeros((n_sample, ncol), dtype=np.float64)
    for c in range(ncol):
        cnt = int(counts[c])
        if cnt == 0:
            continue
        vals = _view(int(sample_ptrs[c]), np.float64, cnt)
        rows = _view(int(idx_ptrs[c]), np.int32, cnt)
        sample[rows, c] = vals
    cd = _CDataset(Dataset(sample, params=params, free_raw_data=False),
                   params)
    cd.nrow_total = int(num_total_row)
    cd.ncol = ncol
    cd.pending = np.zeros((cd.nrow_total, ncol), dtype=np.float64)
    cd.sample = sample
    _write_out(out, _register(cd), ctypes.c_uint64)
    return 0


def LGBM_DatasetPushRows(dataset, data, data_type, nrow, ncol,
                         start_row) -> int:
    cd = _get(dataset)
    if cd.pending is None:
        raise LightGBMError("Dataset was not created for streaming push")
    X = _mat_from_ptr(data, data_type, nrow, ncol, 1)
    s = int(start_row)
    cd.pending[s:s + int(nrow)] = X
    cd.pushed += int(nrow)
    if cd.pushed >= cd.nrow_total:
        _finish_pushed(cd)
    return 0


def _finish_pushed(cd: _CDataset) -> None:
    ref = cd.reference.ds if cd.reference is not None else None
    cd.ds = Dataset(cd.pending, params=cd.params, reference=ref,
                    free_raw_data=False)
    cd.ds.construct()
    cd.pending = None


def LGBM_DatasetPushRowsByCSR(dataset, indptr, indptr_type, indices, data,
                              data_type, nindptr, nelem, num_col,
                              start_row) -> int:
    cd = _get(dataset)
    if cd.pending is None:
        raise LightGBMError("Dataset was not created for streaming push")
    ip = _indptr_view(indptr, indptr_type, int(nindptr))
    idx = _view(indices, np.int32, int(nelem))
    vals = _view(data, _NP_DTYPE[int(data_type)], int(nelem))
    nrow = int(nindptr) - 1
    s = int(start_row)
    for r in range(nrow):
        a, b = int(ip[r]), int(ip[r + 1])
        cd.pending[s + r, :] = 0.0
        cd.pending[s + r, idx[a:b]] = vals[a:b]
    cd.pushed += nrow
    if cd.pushed >= cd.nrow_total:
        _finish_pushed(cd)
    return 0


def LGBM_DatasetCreateByReference(reference, num_total_row, out) -> int:
    ref = _get(reference)
    cd = _CDataset(Dataset(None, free_raw_data=False), dict(ref.params))
    cd.reference = ref
    cd.nrow_total = int(num_total_row)
    cd.ncol = ref.construct().num_feature()
    cd.pending = np.zeros((cd.nrow_total, cd.ncol), dtype=np.float64)
    _write_out(out, _register(cd), ctypes.c_uint64)
    return 0


def LGBM_DatasetGetSubset(handle, used_row_indices, num_used_row_indices,
                          parameters, out) -> int:
    cd = _get(handle)
    idx = np.array(_view(used_row_indices, np.int32,
                         int(num_used_row_indices)), copy=True)
    params = _params_dict(parameters)
    sub = cd.construct().subset(idx, params=params or None)
    sub.construct()
    _write_out(out, _register(_CDataset(sub, params)), ctypes.c_uint64)
    return 0


def LGBM_DatasetSetFeatureNames(handle, feature_names, num_feature) -> int:
    cd = _get(handle)
    ptrs = _view(feature_names, np.uint64, int(num_feature))
    names = [ctypes.string_at(int(p)).decode("utf-8") for p in ptrs]
    cd.construct()
    cd.ds._inner.feature_names = names
    return 0


def LGBM_DatasetGetFeatureNames(handle, out_strs, num_feature) -> int:
    """v2.3.2 ABI parity: caller-allocated, unbounded buffers — see
    LGBM_BoosterGetFeatureNames."""
    cd = _get(handle)
    names = cd.construct().get_feature_name()
    _write_out(num_feature, len(names), ctypes.c_int32)
    ptrs = _view(out_strs, np.uint64, len(names))
    for i, n in enumerate(names):
        raw = n.encode("utf-8") + b"\0"
        ctypes.memmove(int(ptrs[i]), raw, len(raw))
    return 0


def LGBM_DatasetFree(handle) -> int:
    _handles.pop(int(handle), None)
    return 0


def LGBM_DatasetSaveBinary(handle, filename) -> int:
    cd = _get(handle)
    if isinstance(filename, bytes):
        filename = filename.decode("utf-8")
    cd.construct()._inner.save_binary(str(filename))
    return 0


def LGBM_DatasetDumpText(handle, filename) -> int:
    cd = _get(handle)
    if isinstance(filename, bytes):
        filename = filename.decode("utf-8")
    inner = cd.construct()._inner
    with open(str(filename), "w") as f:
        f.write("num_data: %d\n" % inner.num_data)
        f.write("num_features: %d\n" % inner.num_total_features)
        f.write("feature_names: %s\n" % " ".join(inner.feature_names))
    return 0


_FIELD_DTYPE = {"label": np.float32, "weight": np.float32,
                "init_score": np.float64, "group": np.int32,
                "query": np.int32}


def LGBM_DatasetSetField(handle, field_name, field_data, num_element,
                         type_) -> int:
    cd = _get(handle)
    if isinstance(field_name, bytes):
        field_name = field_name.decode("utf-8")
    name = "group" if field_name == "query" else field_name
    arr = np.array(_view(field_data, _NP_DTYPE[int(type_)],
                         int(num_element)), copy=True)
    cd.construct().set_field(name, arr)
    return 0


def LGBM_DatasetGetField(handle, field_name, out_len, out_ptr,
                         out_type) -> int:
    cd = _get(handle)
    if isinstance(field_name, bytes):
        field_name = field_name.decode("utf-8")
    name = "group" if field_name == "query" else field_name
    val = cd.construct().get_field(name)
    if val is None:
        _write_out(out_len, 0, ctypes.c_int32)
        raise LightGBMError("Field %s is not set" % field_name)
    dt = _FIELD_DTYPE.get(name, np.float32)
    arr = np.ascontiguousarray(val, dtype=dt)
    if name == "group":
        # reference returns query BOUNDARIES [nq+1], not sizes
        arr = np.concatenate([[0], np.cumsum(arr)]).astype(np.int32)
    cd._field_cache = arr   # keep alive: caller reads the raw pointer
    _write_out(out_len, arr.size, ctypes.c_int32)
    _write_out(out_ptr, arr.ctypes.data, ctypes.c_uint64)
    code = {np.dtype(np.float32): C_API_DTYPE_FLOAT32,
            np.dtype(np.float64): C_API_DTYPE_FLOAT64,
            np.dtype(np.int32): C_API_DTYPE_INT32}[arr.dtype]
    _write_out(out_type, code, ctypes.c_int32)
    return 0


def LGBM_DatasetUpdateParamChecking(old_parameters, new_parameters) -> int:
    return 0


def LGBM_DatasetGetNumData(handle, out) -> int:
    _write_out(out, _get(handle).construct().num_data(), ctypes.c_int32)
    return 0


def LGBM_DatasetGetNumFeature(handle, out) -> int:
    _write_out(out, _get(handle).construct().num_feature(), ctypes.c_int32)
    return 0


def LGBM_DatasetAddFeaturesFrom(target, source) -> int:
    tgt, src = _get(target), _get(source)
    tgt.construct().add_features_from(src.construct())
    return 0


# ---------------------------------------------------------------------------
# Booster (c_api.h:387-1006)
# ---------------------------------------------------------------------------

class _CBooster:
    def __init__(self, booster: Booster, train: Optional[_CDataset]):
        self.booster = booster
        self.train = train
        self.valids: List[_CDataset] = []


def LGBM_BoosterCreate(train_data, parameters, out) -> int:
    cd = _get(train_data)
    params = _params_dict(parameters)
    bst = Booster(params=params, train_set=cd.construct())
    _write_out(out, _register(_CBooster(bst, cd)), ctypes.c_uint64)
    return 0


def LGBM_BoosterCreateFromModelfile(filename, out_num_iterations,
                                    out) -> int:
    if isinstance(filename, bytes):
        filename = filename.decode("utf-8")
    bst = Booster(model_file=str(filename))
    _write_out(out_num_iterations, bst.current_iteration, ctypes.c_int32)
    _write_out(out, _register(_CBooster(bst, None)), ctypes.c_uint64)
    return 0


def LGBM_BoosterLoadModelFromString(model_str, out_num_iterations,
                                    out) -> int:
    if isinstance(model_str, bytes):
        model_str = model_str.decode("utf-8")
    bst = Booster(model_str=str(model_str))
    _write_out(out_num_iterations, bst.current_iteration, ctypes.c_int32)
    _write_out(out, _register(_CBooster(bst, None)), ctypes.c_uint64)
    return 0


def LGBM_BoosterFree(handle) -> int:
    _handles.pop(int(handle), None)
    return 0


def LGBM_BoosterShuffleModels(handle, start_iter, end_iter) -> int:
    raise LightGBMError("LGBM_BoosterShuffleModels is not supported on "
                        "device_type=tpu")


def LGBM_BoosterMerge(handle, other_handle) -> int:
    dst, src = _get(handle), _get(other_handle)
    dst.booster._booster._materialize_pending()
    src.booster._booster._materialize_pending()
    dst.booster._booster.models.extend(src.booster._booster.models)
    return 0


def LGBM_BoosterAddValidData(handle, valid_data) -> int:
    cb, cd = _get(handle), _get(valid_data)
    cb.booster.add_valid(cd.construct(),
                         "valid_%d" % (len(cb.valids) + 1))
    cb.valids.append(cd)
    return 0


def LGBM_BoosterResetTrainingData(handle, train_data) -> int:
    raise LightGBMError("LGBM_BoosterResetTrainingData is not supported on "
                        "device_type=tpu; create a new booster")


def LGBM_BoosterResetParameter(handle, parameters) -> int:
    """GBDT::ResetConfig (gbdt.cpp:704): training-control updates
    (learning rate, regularization, sampling, bagging, tree shape) take
    effect at the next iteration — static grower knobs recompile the
    device program; structurally-fixed keys (objective, max_bin, ...)
    warn and are skipped."""
    cb = _get(handle)
    params = _params_dict(parameters)
    cb.booster.params.update(params)
    cb.booster._booster.reset_config(params)
    return 0


def LGBM_BoosterGetNumClasses(handle, out_len) -> int:
    _write_out(out_len, _get(handle).booster._booster.num_class,
               ctypes.c_int32)
    return 0


def LGBM_BoosterUpdateOneIter(handle, is_finished) -> int:
    fin = _get(handle).booster.update()
    _write_out(is_finished, 1 if fin else 0, ctypes.c_int32)
    return 0


def LGBM_BoosterUpdateOneIterCustom(handle, grad, hess, is_finished) -> int:
    cb = _get(handle)
    inner = cb.booster._booster
    n = inner.num_data * inner.num_tree_per_iteration
    g = np.array(_view(grad, np.float32, n), copy=True)
    h = np.array(_view(hess, np.float32, n), copy=True)
    fin = inner.train_one_iter(g, h)
    _write_out(is_finished, 1 if fin else 0, ctypes.c_int32)
    return 0


def LGBM_BoosterRefit(handle, leaf_preds, nrow, ncol) -> int:
    cb = _get(handle)
    if cb.train is None:
        raise LightGBMError("Refit requires a booster with training data")
    X = cb.train.construct()._raw_X
    if X is None:
        raise LightGBMError("Refit requires raw training data "
                            "(free_raw_data=False)")
    cb.booster._booster.refit(X)
    return 0


def LGBM_BoosterRollbackOneIter(handle) -> int:
    _get(handle).booster.rollback_one_iter()
    return 0


def LGBM_BoosterGetCurrentIteration(handle, out_iteration) -> int:
    _write_out(out_iteration, _get(handle).booster.current_iteration,
               ctypes.c_int32)
    return 0


def LGBM_BoosterNumModelPerIteration(handle, out) -> int:
    _write_out(out, _get(handle).booster.num_model_per_iteration(),
               ctypes.c_int32)
    return 0


def LGBM_BoosterNumberOfTotalModel(handle, out) -> int:
    _write_out(out, _get(handle).booster.num_trees(), ctypes.c_int32)
    return 0


def _eval_names(cb: _CBooster) -> List[str]:
    names = []
    for m in cb.booster._metrics:
        names.extend(m.names)
    return names


def LGBM_BoosterGetEvalCounts(handle, out_len) -> int:
    _write_out(out_len, len(_eval_names(_get(handle))), ctypes.c_int32)
    return 0


def LGBM_BoosterGetEvalNames(handle, out_len, out_strs) -> int:
    names = _eval_names(_get(handle))
    _write_out(out_len, len(names), ctypes.c_int32)
    ptrs = _view(out_strs, np.uint64, len(names))
    for i, n in enumerate(names):
        raw = n.encode("utf-8") + b"\0"
        ctypes.memmove(int(ptrs[i]), raw, len(raw))
    return 0


def LGBM_BoosterGetFeatureNames(handle, out_len, out_strs) -> int:
    """v2.3.2 ABI parity: out_strs must point at caller-allocated buffers
    each large enough for the NUL-terminated name (the reference added
    buffer_len bounds only in later releases); shorter buffers overflow
    exactly as they do against the reference .so."""
    names = _get(handle).booster.feature_name()
    _write_out(out_len, len(names), ctypes.c_int32)
    ptrs = _view(out_strs, np.uint64, len(names))
    for i, n in enumerate(names):
        raw = n.encode("utf-8") + b"\0"
        ctypes.memmove(int(ptrs[i]), raw, len(raw))
    return 0


def LGBM_BoosterGetNumFeature(handle, out_len) -> int:
    _write_out(out_len, _get(handle).booster.num_feature(), ctypes.c_int32)
    return 0


def LGBM_BoosterGetEval(handle, data_idx, out_len, out_results) -> int:
    """data_idx 0 = train, >=1 = valid sets (c_api.h:597)."""
    cb = _get(handle)
    if int(data_idx) == 0:
        res = cb.booster.eval_train()
    else:
        b = cb.booster._booster
        i = int(data_idx) - 1
        res = cb.booster._eval_one(b.valid_score[i].score_host(),
                                   b.valid_metrics[i],
                                   b.valid_names[i])
    vals = np.asarray([r[2] for r in res], dtype=np.float64)
    _write_out(out_len, vals.size, ctypes.c_int32)
    if vals.size:
        ctypes.memmove(int(out_results), vals.ctypes.data, vals.nbytes)
    return 0


def LGBM_BoosterGetNumPredict(handle, data_idx, out_len) -> int:
    cb = _get(handle)
    b = cb.booster._booster
    if int(data_idx) == 0:
        n = b.num_data
    else:
        n = b.valid_score[int(data_idx) - 1].num_data
    _write_out(out_len, n * b.num_tree_per_iteration, ctypes.c_int64)
    return 0


def LGBM_BoosterGetPredict(handle, data_idx, out_len, out_result) -> int:
    cb = _get(handle)
    b = cb.booster._booster
    if int(data_idx) == 0:
        score = b.train_score.score_host()
    else:
        score = b.valid_score[int(data_idx) - 1].score_host()
    ntpi = b.num_tree_per_iteration
    raw = np.asarray(score, dtype=np.float64).reshape(ntpi, -1)
    if b.objective is not None:
        conv = b.objective.convert_output(
            raw[0] if ntpi == 1 else raw.T)
        out = np.ascontiguousarray(conv, dtype=np.float64).reshape(-1)
    else:
        out = raw.T.reshape(-1)
    _write_out(out_len, out.size, ctypes.c_int64)
    ctypes.memmove(int(out_result), out.ctypes.data, out.nbytes)
    return 0


def _predict(cb: _CBooster, X: np.ndarray, predict_type, num_iteration,
             parameter) -> np.ndarray:
    params = _params_dict(parameter)
    pt = int(predict_type)
    kwargs = {}
    for k in ("pred_early_stop", "pred_early_stop_freq",
              "pred_early_stop_margin"):
        if k in params:
            v = params[k]
            kwargs[k] = (v.lower() in ("true", "1", "+")
                         if k == "pred_early_stop" else float(v))
    if "predict_device" in params:   # device inference via the C ABI too
        kwargs["predict_device"] = params["predict_device"]
    out = cb.booster.predict(
        X, num_iteration=int(num_iteration) if int(num_iteration) else None,
        raw_score=(pt == C_API_PREDICT_RAW_SCORE),
        pred_leaf=(pt == C_API_PREDICT_LEAF_INDEX),
        pred_contrib=(pt == C_API_PREDICT_CONTRIB), **kwargs)
    return np.ascontiguousarray(out, dtype=np.float64)


def LGBM_BoosterCalcNumPredict(handle, num_row, predict_type, num_iteration,
                               out_len) -> int:
    cb = _get(handle)
    b = cb.booster._booster
    ntpi = b.num_tree_per_iteration
    niter = b.current_iteration
    if int(num_iteration) > 0:
        niter = min(niter, int(num_iteration))
    pt = int(predict_type)
    if pt == C_API_PREDICT_LEAF_INDEX:
        per_row = niter * ntpi
    elif pt == C_API_PREDICT_CONTRIB:
        per_row = (b.max_feature_idx + 2) * ntpi
    else:
        per_row = ntpi
    _write_out(out_len, int(num_row) * per_row, ctypes.c_int64)
    return 0


def LGBM_BoosterPredictForMat(handle, data, data_type, nrow, ncol,
                              is_row_major, predict_type, num_iteration,
                              parameter, out_len, out_result) -> int:
    cb = _get(handle)
    X = _mat_from_ptr(data, data_type, nrow, ncol, is_row_major)
    out = _predict(cb, X, predict_type, num_iteration, parameter)
    _write_out(out_len, out.size, ctypes.c_int64)
    ctypes.memmove(int(out_result), out.ctypes.data, out.nbytes)
    return 0


def LGBM_BoosterPredictForMats(handle, nrow_ptrs, data_type, nrow, ncol,
                               predict_type, num_iteration, parameter,
                               out_len, out_result) -> int:
    cb = _get(handle)
    ptrs = _view(nrow_ptrs, np.uint64, int(nrow))
    rows = [_view(int(p), _NP_DTYPE[int(data_type)], int(ncol))
            for p in ptrs]
    X = np.asarray(rows, dtype=np.float64)
    out = _predict(cb, X, predict_type, num_iteration, parameter)
    _write_out(out_len, out.size, ctypes.c_int64)
    ctypes.memmove(int(out_result), out.ctypes.data, out.nbytes)
    return 0


def LGBM_BoosterPredictForMatSingleRow(handle, data, data_type, ncol,
                                       is_row_major, predict_type,
                                       num_iteration, parameter, out_len,
                                       out_result) -> int:
    return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                     is_row_major, predict_type,
                                     num_iteration, parameter, out_len,
                                     out_result)


def LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices, data,
                              data_type, nindptr, nelem, num_col,
                              predict_type, num_iteration, parameter,
                              out_len, out_result) -> int:
    cb = _get(handle)
    ip = _indptr_view(indptr, indptr_type, int(nindptr))
    idx = _view(indices, np.int32, int(nelem))
    vals = _view(data, _NP_DTYPE[int(data_type)], int(nelem))
    nrow = int(nindptr) - 1
    X = np.zeros((nrow, int(num_col)), dtype=np.float64)
    for r in range(nrow):
        s, e = int(ip[r]), int(ip[r + 1])
        X[r, idx[s:e]] = vals[s:e]
    out = _predict(cb, X, predict_type, num_iteration, parameter)
    _write_out(out_len, out.size, ctypes.c_int64)
    ctypes.memmove(int(out_result), out.ctypes.data, out.nbytes)
    return 0


def LGBM_BoosterPredictForCSRSingleRow(handle, indptr, indptr_type, indices,
                                       data, data_type, nindptr, nelem,
                                       num_col, predict_type, num_iteration,
                                       parameter, out_len,
                                       out_result) -> int:
    return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                     data, data_type, nindptr, nelem,
                                     num_col, predict_type, num_iteration,
                                     parameter, out_len, out_result)


def LGBM_BoosterPredictForCSC(handle, col_ptr, col_ptr_type, indices, data,
                              data_type, ncol_ptr, nelem, num_row,
                              predict_type, num_iteration, parameter,
                              out_len, out_result) -> int:
    cb = _get(handle)
    cp = _indptr_view(col_ptr, col_ptr_type, int(ncol_ptr))
    idx = _view(indices, np.int32, int(nelem))
    vals = _view(data, _NP_DTYPE[int(data_type)], int(nelem))
    ncol = int(ncol_ptr) - 1
    X = np.zeros((int(num_row), ncol), dtype=np.float64)
    for c in range(ncol):
        s, e = int(cp[c]), int(cp[c + 1])
        X[idx[s:e], c] = vals[s:e]
    out = _predict(cb, X, predict_type, num_iteration, parameter)
    _write_out(out_len, out.size, ctypes.c_int64)
    ctypes.memmove(int(out_result), out.ctypes.data, out.nbytes)
    return 0


def LGBM_BoosterPredictForFile(handle, data_filename, data_has_header,
                               predict_type, num_iteration, parameter,
                               result_filename) -> int:
    from .data.loader import load_text_file
    cb = _get(handle)
    if isinstance(data_filename, bytes):
        data_filename = data_filename.decode("utf-8")
    if isinstance(result_filename, bytes):
        result_filename = result_filename.decode("utf-8")
    cfg = params_to_config(_params_dict(parameter))
    cfg.header = bool(data_has_header)
    loaded = load_text_file(str(data_filename), cfg)
    out = _predict(cb, loaded.X, predict_type, num_iteration, parameter)
    if out.ndim == 1:
        out = out.reshape(-1, 1)
    np.savetxt(str(result_filename), out, fmt="%.10g", delimiter="\t")
    return 0


def LGBM_BoosterSaveModel(handle, start_iteration, num_iteration,
                          filename) -> int:
    cb = _get(handle)
    if isinstance(filename, bytes):
        filename = filename.decode("utf-8")
    text = cb.booster._booster.save_model_to_string(
        int(start_iteration),
        int(num_iteration) if int(num_iteration) else -1)
    with open(str(filename), "w") as f:
        f.write(text)
    return 0


def LGBM_BoosterSaveModelToString(handle, start_iteration, num_iteration,
                                  buffer_len, out_len, out_str) -> int:
    cb = _get(handle)
    text = cb.booster._booster.save_model_to_string(
        int(start_iteration),
        int(num_iteration) if int(num_iteration) else -1)
    raw = text.encode("utf-8") + b"\0"
    _write_out(out_len, len(raw), ctypes.c_int64)
    if int(buffer_len) >= len(raw):
        ctypes.memmove(int(out_str), raw, len(raw))
    return 0


def LGBM_BoosterDumpModel(handle, start_iteration, num_iteration,
                          buffer_len, out_len, out_str) -> int:
    cb = _get(handle)
    d = cb.booster._booster.dump_model(
        int(start_iteration),
        int(num_iteration) if int(num_iteration) else -1)
    raw = json.dumps(d).encode("utf-8") + b"\0"
    _write_out(out_len, len(raw), ctypes.c_int64)
    if int(buffer_len) >= len(raw):
        ctypes.memmove(int(out_str), raw, len(raw))
    return 0


def LGBM_BoosterGetLeafValue(handle, tree_idx, leaf_idx, out_val) -> int:
    cb = _get(handle)
    cb.booster._booster._materialize_pending()
    tree = cb.booster._booster.models[int(tree_idx)]
    ctypes.c_double.from_address(int(out_val)).value = float(
        tree.leaf_value[int(leaf_idx)])
    return 0


def LGBM_BoosterSetLeafValue(handle, tree_idx, leaf_idx, val) -> int:
    cb = _get(handle)
    cb.booster._booster._materialize_pending()
    tree = cb.booster._booster.models[int(tree_idx)]
    tree.set_leaf_output(int(leaf_idx), float(val))
    return 0


def LGBM_BoosterFeatureImportance(handle, num_iteration, importance_type,
                                  out_results) -> int:
    cb = _get(handle)
    kind = "split" if int(importance_type) == 0 else "gain"
    imp = cb.booster._booster.feature_importance(
        kind, int(num_iteration) if int(num_iteration) else 0)
    arr = np.ascontiguousarray(imp, dtype=np.float64)
    ctypes.memmove(int(out_results), arr.ctypes.data, arr.nbytes)
    return 0


def LGBM_BoosterGetUpperBoundValue(handle, out_results) -> int:
    cb = _get(handle)
    cb.booster._booster._materialize_pending()
    total = 0.0
    for t in cb.booster._booster.models:
        nl = max(t.num_leaves, 1)
        total += float(np.max(t.leaf_value[:nl]))
    ctypes.c_double.from_address(int(out_results)).value = total
    return 0


def LGBM_BoosterGetLowerBoundValue(handle, out_results) -> int:
    cb = _get(handle)
    cb.booster._booster._materialize_pending()
    total = 0.0
    for t in cb.booster._booster.models:
        nl = max(t.num_leaves, 1)
        total += float(np.min(t.leaf_value[:nl]))
    ctypes.c_double.from_address(int(out_results)).value = total
    return 0


# ---------------------------------------------------------------------------
# Network (c_api.h:1017-1036) — single-process JAX meshes replace socket
# rank wiring; multi-host runs initialize jax.distributed out of band.
# ---------------------------------------------------------------------------

def LGBM_NetworkInit(machines, local_listen_port, listen_time_out,
                     num_machines) -> int:
    if int(num_machines) > 1:
        Log.warning(
            "LGBM_NetworkInit: socket machine lists are not used on "
            "device_type=tpu; distributed training shards over the JAX "
            "mesh (tree_learner=data/voting/feature + jax.distributed)")
    return 0


def LGBM_NetworkFree() -> int:
    return 0


def LGBM_NetworkInitWithFunctions(num_machines, rank, reduce_scatter_ext_fun,
                                  allgather_ext_fun) -> int:
    raise LightGBMError(
        "External collective function injection is not supported; the TPU "
        "backend's collectives are XLA psum_scatter/all_gather over the "
        "device mesh")
