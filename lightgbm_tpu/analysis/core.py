"""Shared lint primitives: findings and the per-module AST context.

The rules in :mod:`lightgbm_tpu.analysis.rules` are pure functions over a
:class:`ModuleContext` — one parsed module plus the derived maps every
rule needs (parent links, import alias resolution, jit/kernel scope
classification, loop nesting). Building those once per file keeps each
rule to a dozen lines of actual logic.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .config import GraftlintConfig

# inline suppression grammar:
#   x = risky()            # graftlint: disable=JG003
#   # graftlint: disable=JG002,JG004   (on the line above also works)
#   # graftlint: skip-file             (first 10 lines: whole module)
_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")
_SKIP_FILE_RE = re.compile(r"#\s*graftlint:\s*skip-file")


@dataclass
class Finding:
    """One lint hit. `snippet` (the stripped source line) is part of the
    identity used for baseline matching, so findings survive line drift."""

    rule: str
    path: str            # repo-relative, '/' separated
    line: int            # 1-based
    col: int
    message: str
    snippet: str
    suppressed: bool = False
    suppression: str = ""        # "inline" | "baseline"
    # optional autofix: ("replace_span", (lineno, end_lineno, new_text)),
    # new_text == None means delete the statement lines outright
    fix: Optional[Tuple[str, tuple]] = None

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet, "suppressed": self.suppressed,
                "suppression": self.suppression}


class ModuleContext:
    """One parsed module + the derived maps rules share."""

    def __init__(self, source: str, relpath: str, config: GraftlintConfig):
        self.source = source
        self.relpath = relpath.replace("\\", "/")
        self.config = config
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.aliases = self._collect_aliases()
        self._kernel_res = config.kernel_regexes()
        self.jit_scopes = self._collect_jit_scopes()
        self._disabled_lines = self._collect_suppressions()
        self.skip_file = any(_SKIP_FILE_RE.search(ln)
                             for ln in self.lines[:10])

    # -- imports ------------------------------------------------------
    def _collect_aliases(self) -> Dict[str, str]:
        """local name -> dotted origin ('jnp' -> 'jax.numpy'; a relative
        'from .pallas_compat import pl' -> '.pallas_compat.pl')."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = mod + "." + a.name
        return aliases

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolved dotted name of a Name/Attribute chain, with the root
        segment mapped through the module's import aliases."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def call_target(self, call: ast.Call) -> Optional[str]:
        return self.dotted(call.func)

    # -- scopes -------------------------------------------------------
    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parent.get(cur)
        return None

    def _decorator_is_jit(self, dec: ast.AST) -> bool:
        d = self.dotted(dec)
        if d in ("jax.jit", "jax.pmap", "jit"):
            return True
        if isinstance(dec, ast.Call):
            target = self.dotted(dec.func)
            if target in ("jax.jit", "jax.pmap", "jit"):
                return True
            if target in ("functools.partial", "partial") and dec.args:
                return self.dotted(dec.args[0]) in ("jax.jit", "jax.pmap",
                                                    "jit")
        return False

    def is_kernel_name(self, name: str) -> bool:
        return any(r.search(name) for r in self._kernel_res)

    def _collect_jit_scopes(self) -> Set[ast.AST]:
        """Functions whose bodies trace: jit-decorated ones, kernel-named
        ones, and everything (transitively) nested inside either."""
        scopes: Set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if any(self._decorator_is_jit(d) for d in node.decorator_list) \
                    or self.is_kernel_name(node.name):
                scopes.add(node)
        # transitive nesting
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node not in scopes:
                    fn = self.enclosing_function(node)
                    if fn is not None and fn in scopes:
                        scopes.add(node)
                        changed = True
        return scopes

    def in_jit_scope(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        return fn is not None and fn in self.jit_scopes

    def in_kernel_scope(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and self.is_kernel_name(fn.name):
                return True
            fn = self.enclosing_function(fn)
        return False

    def in_host_loop(self, node: ast.AST) -> bool:
        """Inside a for/while body, not crossing a function boundary (a
        function *defined* in a loop does not run per iteration)."""
        cur = self.parent.get(node)
        child = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            if isinstance(cur, (ast.For, ast.While)) \
                    and child in getattr(cur, "body", []) + \
                    getattr(cur, "orelse", []):
                return True
            child = cur
            cur = self.parent.get(cur)
        return False

    # -- suppression --------------------------------------------------
    def _collect_suppressions(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                out[i] = ids
        return out

    def is_inline_suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            ids = self._disabled_lines.get(ln)
            if ids and (rule in ids or "ALL" in ids):
                # a line-above suppression must be a pure comment line
                if ln == line - 1 and ln >= 1 \
                        and not self.lines[ln - 1].lstrip().startswith("#"):
                    continue
                return True
        return False

    # -- findings -----------------------------------------------------
    def finding(self, rule: str, node: ast.AST, message: str,
                fix=None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=snippet, fix=fix)
