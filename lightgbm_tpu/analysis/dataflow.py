"""Abstract interpretation over closed jaxprs: dtype, range, error.

Every jaxpr audit before this module was a bespoke recursive walk:
``jaxpr_audit`` re-implemented sub-jaxpr traversal per check and could
only ask *structural* questions (is there a ``convert_element_type`` to
f64 anywhere?).  It could not see an f64 constant closed over inside a
``custom_jvp`` body (consts are not equation outputs), and it could not
say whether a narrowing is *safe* — that needs to know what values flow
through it.  This module is the shared engine those audits (and the new
precision-flow / transfer / quantization auditors) run on: a forward
abstract interpreter that propagates, per value,

* a **dtype** (read off the avals — exact, this is jax's own type
  lattice; the analysis records where f64 appears and where a float
  narrows),
* an **interval** value-range domain seeded from input contracts (bin
  indices in ``[0, max_bin)``, counts in ``[0, rows]``, hessians >= 0 —
  the ops modules export these as ``*_input_contract`` annotations),
* an accumulated **absolute error bound** versus exact real arithmetic
  (unit roundoff per float dtype, classic forward-error recurrences per
  primitive — see the rule table),

through every primitive *including all sub-jaxpr carriers* (``pjit``,
``scan``, ``while``, ``cond``, ``custom_jvp_call``/``custom_vjp_call``,
``closed_call``, ``xla_pmap``) with a fixpoint for loop bodies:

* a ``scan`` with a small static ``length`` is unrolled exactly (the
  carry bound is tight: summing L values in [0, 1] proves [0, L]);
* longer scans and ``while`` loops iterate the body to a join-fixpoint,
  widening unstable bounds to +-inf after :data:`WIDEN_AFTER` rounds so
  termination is guaranteed (``report.fixpoint`` records rounds /
  converged / widened for the tests to pin).

Soundness posture: unknown primitives degrade to TOP (unbounded range,
unknown error) — the analysis never *invents* a bound, so a "proven"
range out of :func:`interpret` is trustworthy while an unbounded one
just means "could not prove".  Loop-replayed sites JOIN into one record
per equation (interval hull, max error), so a narrowing inside a scan
body reports the bound over every iteration.

Site records the auditors consume:

* ``narrowings`` — every float->narrower-float ``convert_element_type``
  with the incoming range/error and whether the range provably fits the
  target dtype; sites whose result directly feeds a comparison /
  ``reduce_max`` / ``argmax`` are flagged ``decision_relevant`` (the
  tie-flip geometry: range arguments cannot prove those safe, ties flip
  inside the retained ULP — they must be blessed).
* ``f64_sites`` — f64-producing equations AND f64 consts/constvars,
  including ones reached only through call primitives (the class the
  old walk missed).
* ``transfers`` — host/transfer primitives at any loop depth (alias-
  semantics ``device_put`` staging marked benign).
* ``replicated_large`` / ``alias_sites`` — explicit replication ops
  (``all_gather``) over the size threshold, and ``pallas_call``
  ``input_output_aliases`` (the donation/in-place-partition queries).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import events as telemetry

C_VALUES = "analysis::dataflow_values"

INF = float("inf")

# unit roundoff per float dtype (half-ulp of the mantissa)
UNIT_ROUNDOFF = {
    "float64": 2.0 ** -53,
    "float32": 2.0 ** -24,
    "bfloat16": 2.0 ** -8,
    "float16": 2.0 ** -11,
}
# mantissa bits: "narrowing" = strictly fewer (f64 -> f32/bf16/f16,
# f32 -> bf16/f16); bf16 vs f16 conversions are lateral, not narrowing
_MANTISSA = {"float64": 52, "float32": 23, "float16": 10, "bfloat16": 7}
_FLOAT_MAX = {"float64": 1.7976931348623157e308,
              "float32": 3.4028235e38,
              "float16": 65504.0,
              "bfloat16": 3.3895314e38}

# primitives that round-trip to the host or move buffers (the transfer
# audit forbids them outright on device programs; the legacy loop audit
# forbids them inside fori_loop/scan/while bodies)
HOST_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "device_put", "copy_to_host_async",
}
# primitives that explicitly materialize a replicated copy on every
# participant — the "sharding degraded to replicated" detector keys on
# these (plus any future gather-to-all collectives)
REPLICATING_PRIMS = {"all_gather", "all_gather_invariant"}
# a narrowed value directly consumed by one of these is decision-
# relevant: the comparison outcome lives inside the discarded mantissa
_DECISION_PRIMS = {"eq", "ne", "lt", "le", "gt", "ge", "max", "min",
                   "reduce_max", "reduce_min", "argmax", "argmin",
                   "select_n", "sort"}

# loop handling knobs (tests pin both paths)
UNROLL_CAP = 32        # scans with static length <= this unroll exactly
FIXPOINT_MAX = 12      # hard iteration cap for the join-fixpoint
WIDEN_AFTER = 3        # rounds of plain joins before widening kicks in

_F64 = np.dtype("float64")


# ---------------------------------------------------------------------------
# domains
# ---------------------------------------------------------------------------

def _pmul(a: float, b: float) -> float:
    """Interval-product term: 0 * inf is 0 here (a value pinned at zero
    stays zero no matter the other factor's bound)."""
    if a == 0.0 or b == 0.0:
        return 0.0
    return a * b


@dataclass(frozen=True)
class Interval:
    """A closed real interval; +-inf bounds mean "unproven"."""

    lo: float = -INF
    hi: float = INF

    @staticmethod
    def top() -> "Interval":
        return Interval(-INF, INF)

    @staticmethod
    def exact(v: float) -> "Interval":
        v = float(v)
        return Interval(v, v)

    @property
    def bounded(self) -> bool:
        return math.isfinite(self.lo) and math.isfinite(self.hi)

    def mag(self) -> float:
        """max |x| over the interval (inf when unbounded)."""
        return max(abs(self.lo), abs(self.hi))

    def join(self, other: "Interval") -> "Interval":
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))

    def widen(self, newer: "Interval") -> "Interval":
        """Classic interval widening: a bound still moving after the
        join rounds jumps straight to +-inf so fixpoints terminate."""
        return Interval(-INF if newer.lo < self.lo else self.lo,
                        INF if newer.hi > self.hi else self.hi)

    def add(self, o: "Interval") -> "Interval":
        return Interval(self.lo + o.lo, self.hi + o.hi)

    def sub(self, o: "Interval") -> "Interval":
        return Interval(self.lo - o.hi, self.hi - o.lo)

    def neg(self) -> "Interval":
        return Interval(-self.hi, -self.lo)

    def mul(self, o: "Interval") -> "Interval":
        ps = (_pmul(self.lo, o.lo), _pmul(self.lo, o.hi),
              _pmul(self.hi, o.lo), _pmul(self.hi, o.hi))
        return Interval(min(ps), max(ps))

    def scale(self, k: float) -> "Interval":
        ps = (_pmul(self.lo, k), _pmul(self.hi, k))
        return Interval(min(ps), max(ps))

    def square(self) -> "Interval":
        if self.lo >= 0.0:
            return Interval(_pmul(self.lo, self.lo),
                            _pmul(self.hi, self.hi))
        if self.hi <= 0.0:
            return Interval(_pmul(self.hi, self.hi),
                            _pmul(self.lo, self.lo))
        return Interval(0.0, _pmul(self.mag(), self.mag()))


@dataclass
class AbsVal:
    """One abstract value: dtype + shape (from the aval — exact),
    interval range, and an accumulated absolute error bound (vs exact
    real arithmetic; inf = unknown)."""

    dtype: Optional[np.dtype]
    shape: Tuple[int, ...]
    rng: Interval
    err: float

    def join(self, other: "AbsVal") -> "AbsVal":
        return AbsVal(self.dtype, self.shape, self.rng.join(other.rng),
                      max(self.err, other.err))

    def nbytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n * (self.dtype.itemsize if self.dtype is not None else 1)


def _dtype_name(dt) -> str:
    return np.dtype(dt).name if dt is not None else "?"


def _roundoff(dt) -> float:
    return UNIT_ROUNDOFF.get(_dtype_name(dt), 0.0)


def is_narrowing(src, dst) -> bool:
    """float -> float conversion losing mantissa bits (f64->f32/bf16/
    f16, f32->bf16/f16)."""
    s, d = _dtype_name(src), _dtype_name(dst)
    return (s in _MANTISSA and d in _MANTISSA
            and _MANTISSA[d] < _MANTISSA[s])


def _default_for_aval(aval, err: float = INF) -> AbsVal:
    dt = getattr(aval, "dtype", None)
    shape = tuple(getattr(aval, "shape", ()) or ())
    if dt is None:
        return AbsVal(None, shape, Interval.top(), err)
    dt = np.dtype(dt)
    if dt.kind == "b":
        return AbsVal(dt, shape, Interval(0.0, 1.0), 0.0)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return AbsVal(dt, shape, Interval(float(info.min),
                                          float(info.max)), 0.0)
    return AbsVal(dt, shape, Interval.top(), err)


def _const_absval(c) -> AbsVal:
    arr = np.asarray(c)
    rng = Interval.top()
    if arr.size and arr.dtype.kind in "iufb":
        lo = float(arr.min())
        hi = float(arr.max())
        if math.isfinite(lo) and math.isfinite(hi):
            rng = Interval(lo, hi)
    return AbsVal(arr.dtype, tuple(arr.shape), rng, 0.0)


# ---------------------------------------------------------------------------
# site records
# ---------------------------------------------------------------------------

@dataclass
class NarrowSite:
    """One float-narrowing ``convert_element_type`` equation."""

    src: str                    # source dtype name
    dst: str                    # target dtype name
    rng: Interval               # incoming value range (joined over loops)
    err: float                  # incoming accumulated error bound
    depth: int                  # enclosing loop depth
    decision_relevant: bool = False   # result feeds a compare/argmax
    # the source is a weak-typed SCALAR: a python-float literal x64
    # promoted to f64 and narrowed straight back — the JG003 source
    # class, not materialized f64 data flowing through the program
    weak_src: bool = False

    @property
    def fits(self) -> bool:
        """The proven range fits the target dtype's finite span — a
        point interval at +-inf is an exact sentinel (inf is
        representable in every float dtype), not an unproven range."""
        if self.rng.lo == self.rng.hi and self.err == 0.0:
            return abs(self.rng.lo) == INF \
                or abs(self.rng.lo) <= _FLOAT_MAX.get(self.dst, INF)
        return (self.rng.bounded
                and self.rng.mag() <= _FLOAT_MAX.get(self.dst, INF))

    def describe(self) -> str:
        r = ("[%.6g, %.6g]" % (self.rng.lo, self.rng.hi)
             if self.rng.bounded else "unbounded")
        bits = "%s->%s range %s err %.3g" % (self.src, self.dst, r,
                                             self.err)
        if self.decision_relevant:
            bits += " (feeds a comparison)"
        return bits

    def to_dict(self) -> dict:
        return {"src": self.src, "dst": self.dst,
                "lo": self.rng.lo, "hi": self.rng.hi, "err": self.err,
                "depth": self.depth, "fits": self.fits,
                "decision_relevant": self.decision_relevant}


@dataclass
class TransferSite:
    prim: str
    depth: int
    benign: bool      # alias-semantics device_put (const staging)

    def describe(self) -> str:
        return "%s at loop depth %d%s" % (
            self.prim, self.depth, " (alias staging)" if self.benign
            else "")


@dataclass
class DataflowReport:
    """Everything one :func:`interpret` walk learned."""

    n_values: int = 0
    n_eqns: int = 0
    narrowings: List[NarrowSite] = field(default_factory=list)
    f64_sites: List[str] = field(default_factory=list)
    f64_converts: List[str] = field(default_factory=list)
    transfers: List[TransferSite] = field(default_factory=list)
    replicated_large: List[Tuple[str, int, int]] = field(
        default_factory=list)       # (prim, bytes, depth)
    alias_sites: List[Tuple[str, tuple]] = field(default_factory=list)
    fixpoint: Dict[str, object] = field(default_factory=dict)
    out_vals: List[AbsVal] = field(default_factory=list)

    def host_in_loop(self) -> List[str]:
        return [t.prim for t in self.transfers if t.depth > 0]


# ---------------------------------------------------------------------------
# structural walk (the legacy-audit compatibility surface)
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn) -> Iterator:
    """Raw jaxprs reachable through an equation's params (ClosedJaxpr
    or raw, single or in tuples — pjit's ``jaxpr``, call prims'
    ``call_jaxpr``, while's two, cond's ``branches``)."""
    for val in eqn.params.values():
        if hasattr(val, "jaxpr"):          # ClosedJaxpr
            yield val.jaxpr
        elif hasattr(val, "eqns"):         # raw Jaxpr
            yield val
        elif isinstance(val, (list, tuple)):
            for v in val:
                if hasattr(v, "jaxpr"):
                    yield v.jaxpr
                elif hasattr(v, "eqns"):
                    yield v


def iter_eqns(jaxpr, loop_depth: int = 0) -> Iterator[Tuple[object, int]]:
    """(eqn, loop_depth) over a jaxpr and every sub-jaxpr — including
    the ones reached through call primitives (pjit/custom_jvp/
    closed_call); loop_depth counts enclosing while/scan bodies."""
    for eqn in jaxpr.eqns:
        yield eqn, loop_depth
        inner = loop_depth + (1 if eqn.primitive.name in ("while", "scan")
                              else 0)
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, inner)


def _closed_subs(closed) -> Iterator:
    """Every ClosedJaxpr reachable from ``closed`` (itself included) —
    the const-bearing objects the f64-const check must visit."""
    yield closed
    seen = {id(closed)}

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else (val,)
                for v in vals:
                    if hasattr(v, "jaxpr") and id(v) not in seen:
                        seen.add(id(v))
                        yield v
                        yield from walk(v.jaxpr)
                    elif hasattr(v, "eqns"):
                        yield from walk(v)
    yield from walk(closed.jaxpr)


def find_f64_consts(closed) -> List[str]:
    """f64 constants closed over anywhere in a ClosedJaxpr — including
    inside sub-jaxprs reached through call primitives.  These are NOT
    equation outputs, which is exactly why the old per-check walk
    missed them (the custom_jvp regression fixture)."""
    out: List[str] = []
    for sub in _closed_subs(closed):
        for c in getattr(sub, "consts", ()) or ():
            try:
                arr = np.asarray(c)
            except Exception:       # pragma: no cover - exotic consts
                continue
            if arr.dtype == _F64:
                out.append("const f64%s closed over"
                           % (list(arr.shape),))
    return out


def alias_sites(jaxpr) -> List[Tuple[str, tuple]]:
    """(primitive, input_output_aliases) for every aliasing-capable
    call — the donation / in-place-partition contract query."""
    out = []
    for eqn, _ in iter_eqns(jaxpr):
        ioa = eqn.params.get("input_output_aliases")
        if ioa is not None:
            out.append((eqn.primitive.name, tuple(ioa)))
    return out


# ---------------------------------------------------------------------------
# primitive transfer functions
# ---------------------------------------------------------------------------

def _rerr(rng: Interval, prop: float, dt) -> float:
    """Forward error of one rounded float op: propagated error plus one
    roundoff at the result's magnitude."""
    u = _roundoff(dt)
    if u == 0.0:
        return prop
    m = rng.mag()
    if not math.isfinite(m):
        return INF
    return prop + u * m


def _r_add(eqn, vals, out_aval):
    a, b = vals
    rng = a.rng.add(b.rng)
    return rng, _rerr(rng, a.err + b.err, out_aval.dtype)


def _r_sub(eqn, vals, out_aval):
    a, b = vals
    rng = a.rng.sub(b.rng)
    return rng, _rerr(rng, a.err + b.err, out_aval.dtype)


def _r_mul(eqn, vals, out_aval):
    a, b = vals
    rng = a.rng.mul(b.rng)
    prop = (_pmul(a.rng.mag(), b.err) + _pmul(b.rng.mag(), a.err)
            + _pmul(a.err, b.err))
    return rng, _rerr(rng, prop, out_aval.dtype)


def _r_div(eqn, vals, out_aval):
    a, b = vals
    blo, bhi = b.rng.lo, b.rng.hi
    if not b.rng.bounded or blo <= 0.0 <= bhi:
        return Interval.top(), INF
    inv = Interval(min(1.0 / blo, 1.0 / bhi), max(1.0 / blo, 1.0 / bhi))
    rng = a.rng.mul(inv)
    bmin = min(abs(blo), abs(bhi))
    prop = (a.err / bmin
            + _pmul(a.rng.mag(), b.err) / (bmin * bmin))
    return rng, _rerr(rng, prop, out_aval.dtype)


def _r_neg(eqn, vals, out_aval):
    a = vals[0]
    return a.rng.neg(), a.err


def _r_abs(eqn, vals, out_aval):
    a = vals[0]
    lo = 0.0 if a.rng.lo <= 0.0 <= a.rng.hi else min(abs(a.rng.lo),
                                                     abs(a.rng.hi))
    return Interval(lo, a.rng.mag()), a.err


def _r_max(eqn, vals, out_aval):
    a, b = vals
    return (Interval(max(a.rng.lo, b.rng.lo), max(a.rng.hi, b.rng.hi)),
            max(a.err, b.err))


def _r_min(eqn, vals, out_aval):
    a, b = vals
    return (Interval(min(a.rng.lo, b.rng.lo), min(a.rng.hi, b.rng.hi)),
            max(a.err, b.err))


def _r_clamp(eqn, vals, out_aval):
    # clamp(lo, x, hi) = min(max(x, lo), hi) is monotone in every
    # operand, so the interval bounds are the expression applied to
    # the per-operand bounds — correct for non-point clamp bounds too
    # (max(lo.lo, ...) alone would wrongly exclude a reachable hi.lo)
    lo_v, x, hi_v = vals
    lo = min(max(x.rng.lo, lo_v.rng.lo), hi_v.rng.lo)
    hi = min(max(x.rng.hi, lo_v.rng.hi), hi_v.rng.hi)
    return Interval(lo, hi), max(x.err, lo_v.err, hi_v.err)


def _r_select(eqn, vals, out_aval):
    cases = vals[1:] if len(vals) > 1 else vals
    rng, err = cases[0].rng, cases[0].err
    for c in cases[1:]:
        rng = rng.join(c.rng)
        err = max(err, c.err)
    return rng, err


def _r_identity(eqn, vals, out_aval):
    a = vals[0]
    return a.rng, a.err


def _r_join_all(eqn, vals, out_aval):
    rng, err = vals[0].rng, vals[0].err
    for v in vals[1:]:
        rng = rng.join(v.rng)
        err = max(err, v.err)
    return rng, err


def _contract_size(eqn, vals) -> int:
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    shape = vals[0].shape
    k = 1
    for d in lhs_c:
        k *= int(shape[d]) if d < len(shape) else 1
    return max(k, 1)


def _r_dot(eqn, vals, out_aval):
    a, b = vals[0], vals[1]
    k = _contract_size(eqn, vals)
    prod = a.rng.mul(b.rng)
    rng = prod.scale(float(k))
    ma, mb = a.rng.mag(), b.rng.mag()
    u = _roundoff(eqn.params.get("preferred_element_type")
                  or out_aval.dtype)
    prop = k * (_pmul(ma, b.err) + _pmul(mb, a.err)
                + _pmul(a.err, b.err) + _pmul(u, _pmul(ma, mb)))
    if not math.isfinite(prop):
        prop = INF
    return rng, prop


def _reduced_size(eqn, vals) -> int:
    axes = eqn.params.get("axes", ())
    shape = vals[0].shape
    k = 1
    for d in axes:
        k *= int(shape[d]) if d < len(shape) else 1
    return max(k, 1)


def _r_reduce_sum(eqn, vals, out_aval):
    a = vals[0]
    k = _reduced_size(eqn, vals)
    rng = a.rng.scale(float(k))
    u = _roundoff(out_aval.dtype)
    err = k * a.err + _pmul(u * k, rng.mag())
    if not math.isfinite(err):
        err = INF
    return rng, err


def _r_reduce_minmax(eqn, vals, out_aval):
    a = vals[0]
    return a.rng, a.err


def _r_cumsum(eqn, vals, out_aval):
    a = vals[0]
    axis = eqn.params.get("axis", 0)
    shape = vals[0].shape
    n = int(shape[axis]) if axis < len(shape) else 1
    full = a.rng.scale(float(n))
    rng = a.rng.join(full).join(Interval(min(0.0, full.lo),
                                         max(0.0, full.hi)))
    u = _roundoff(out_aval.dtype)
    err = n * a.err + _pmul(u * n, rng.mag())
    if not math.isfinite(err):
        err = INF
    return rng, err


def _mono(fn, dfn_max):
    """Monotone unary float fn with a derivative bound callable."""
    def rule(eqn, vals, out_aval):
        a = vals[0]
        try:
            lo = fn(a.rng.lo)
            hi = fn(a.rng.hi)
        except (ValueError, OverflowError):
            return Interval.top(), INF
        rng = Interval(lo, hi)
        if a.err == 0.0:
            return rng, _rerr(rng, 0.0, out_aval.dtype)
        d = dfn_max(a.rng)
        prop = _pmul(d, a.err) if math.isfinite(d) else INF
        return rng, _rerr(rng, prop, out_aval.dtype)
    return rule


def _safe_exp(x):
    return math.exp(x) if x < 709.0 else INF


def _r_log(eqn, vals, out_aval):
    a = vals[0]
    if a.rng.lo <= 0.0:
        return Interval.top(), INF
    rng = Interval(math.log(a.rng.lo), math.log(a.rng.hi)
                   if math.isfinite(a.rng.hi) else INF)
    prop = a.err / a.rng.lo if a.err else 0.0
    return rng, _rerr(rng, prop, out_aval.dtype)


def _r_sqrt(eqn, vals, out_aval):
    a = vals[0]
    if a.rng.lo < 0.0:
        return Interval.top(), INF
    rng = Interval(math.sqrt(a.rng.lo), math.sqrt(a.rng.hi)
                   if math.isfinite(a.rng.hi) else INF)
    if a.err == 0.0:
        prop = 0.0
    elif a.rng.lo > 0.0:
        prop = a.err / (2.0 * math.sqrt(a.rng.lo))
    else:
        prop = INF
    return rng, _rerr(rng, prop, out_aval.dtype)


def _r_floorlike(fn):
    def rule(eqn, vals, out_aval):
        a = vals[0]
        lo = fn(a.rng.lo) if math.isfinite(a.rng.lo) else a.rng.lo
        hi = fn(a.rng.hi) if math.isfinite(a.rng.hi) else a.rng.hi
        err = 0.0 if a.err == 0.0 else (a.err + 1.0)
        return Interval(lo, hi), err
    return rule


def _r_sign(eqn, vals, out_aval):
    a = vals[0]
    return Interval(-1.0, 1.0), 0.0 if a.err == 0.0 else INF


def _r_integer_pow(eqn, vals, out_aval):
    a = vals[0]
    y = int(eqn.params.get("y", 2))
    if y == 0:
        return Interval(1.0, 1.0), 0.0
    n = abs(y)
    if n == 2:
        rng = a.rng.square()
        prop = 2.0 * _pmul(a.rng.mag(), a.err) + _pmul(a.err, a.err)
        rng, err = rng, _rerr(rng, prop, out_aval.dtype)
    else:
        cur = AbsVal(a.dtype, a.shape, a.rng, a.err)
        for _ in range(n - 1):
            r, e = _r_mul(eqn, [cur, a], out_aval)
            cur = AbsVal(a.dtype, a.shape, r, e)
        rng, err = cur.rng, cur.err
    if y < 0:
        # x ** -n = 1 / x**n: invertible only when x**n is bounded
        # away from zero; anything else is TOP, never a tight lie
        if not rng.bounded or rng.lo <= 0.0 <= rng.hi:
            return Interval.top(), INF
        inv = Interval(min(1.0 / rng.lo, 1.0 / rng.hi),
                       max(1.0 / rng.lo, 1.0 / rng.hi))
        prop = err / (min(abs(rng.lo), abs(rng.hi)) ** 2)
        return inv, _rerr(inv, prop, out_aval.dtype)
    return rng, err


def _r_iota(eqn, vals, out_aval):
    shape = tuple(getattr(out_aval, "shape", ()) or ())
    dim = eqn.params.get("dimension", 0)
    n = int(shape[dim]) if dim < len(shape) else 1
    return Interval(0.0, float(max(n - 1, 0))), 0.0


def _r_bool(eqn, vals, out_aval):
    return Interval(0.0, 1.0), 0.0


def _r_argminmax(eqn, vals, out_aval):
    axes = eqn.params.get("axes", (0,))
    shape = vals[0].shape
    n = 1
    for d in axes:
        n *= int(shape[d]) if d < len(shape) else 1
    return Interval(0.0, float(max(n - 1, 0))), 0.0


def _r_pad(eqn, vals, out_aval):
    a, pv = vals[0], vals[1]
    return a.rng.join(pv.rng), max(a.err, pv.err)


_RULES = {
    "add": _r_add, "sub": _r_sub, "mul": _r_mul, "div": _r_div,
    "neg": _r_neg, "abs": _r_abs, "max": _r_max, "min": _r_min,
    "clamp": _r_clamp, "select_n": _r_select,
    "dot_general": _r_dot,
    "reduce_sum": _r_reduce_sum, "cumsum": _r_cumsum,
    "reduce_max": _r_reduce_minmax, "reduce_min": _r_reduce_minmax,
    "exp": _mono(_safe_exp, lambda r: _safe_exp(r.hi)),
    "log": _r_log, "sqrt": _r_sqrt,
    "tanh": _mono(math.tanh, lambda r: 1.0),
    "logistic": _mono(lambda x: 1.0 / (1.0 + _safe_exp(-x)),
                      lambda r: 0.25),
    "erf": _mono(math.erf, lambda r: 1.13),
    "floor": _r_floorlike(math.floor), "ceil": _r_floorlike(math.ceil),
    "round": _r_floorlike(round),
    "sign": _r_sign, "integer_pow": _r_integer_pow,
    "iota": _r_iota,
    "argmax": _r_argminmax, "argmin": _r_argminmax,
    "eq": _r_bool, "ne": _r_bool, "lt": _r_bool, "le": _r_bool,
    "gt": _r_bool, "ge": _r_bool, "is_finite": _r_bool,
    "and": _r_bool, "or": _r_bool, "not": _r_bool, "xor": _r_bool,
    "broadcast_in_dim": _r_identity, "reshape": _r_identity,
    "transpose": _r_identity, "squeeze": _r_identity,
    "rev": _r_identity, "slice": _r_identity,
    "dynamic_slice": _r_identity, "expand_dims": _r_identity,
    "copy": _r_identity, "stop_gradient": _r_identity,
    "device_put": _r_identity, "gather": _r_identity,
    "convert_element_type": None,       # handled inline (narrow sites)
    "concatenate": _r_join_all, "pad": _r_pad,
    "dynamic_update_slice": lambda e, v, o: _r_join_all(e, v[:2], o),
    "scatter": lambda e, v, o: _r_join_all(e, [v[0], v[-1]], o),
}


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

def _is_alias_device_put(eqn) -> bool:
    sem = eqn.params.get("copy_semantics")
    if not sem:
        return False
    return all("ALIAS" in str(s) for s in sem)


class _Interp:
    def __init__(self, report: DataflowReport,
                 replicated_threshold: int):
        self.report = report
        self.threshold = replicated_threshold
        # site records keyed by equation identity: loop replays JOIN
        # into one record instead of duplicating per iteration
        self._narrow: Dict[int, NarrowSite] = {}
        self._transfer: Dict[int, TransferSite] = {}
        self._f64: Dict[int, str] = {}
        self._conv64: Dict[int, str] = {}
        self._repl: Dict[int, Tuple[str, int, int]] = {}
        self._alias: Dict[int, Tuple[str, tuple]] = {}

    # -- env helpers --------------------------------------------------
    def _read(self, env, atom) -> AbsVal:
        if hasattr(atom, "val"):            # Literal
            return _const_absval(atom.val)
        v = env.get(atom)
        if v is None:
            v = _default_for_aval(atom.aval)
        return v

    # -- one jaxpr ----------------------------------------------------
    def run(self, jaxpr, consts: Sequence[AbsVal],
            args: Sequence[AbsVal], depth: int,
            in_keys: Optional[Sequence[Optional[int]]] = None
            ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        """Interpret one jaxpr.  ``in_keys`` carries narrowing-site
        keys for the inputs and the return pairs each output with its
        key — that is how decision-relevance crosses sub-jaxpr
        boundaries: `jit(argmax)(g32)` must mark g32's narrowing site
        even though the compare lives one call frame down."""
        env: Dict[object, AbsVal] = {}
        cvars = list(jaxpr.constvars)
        for var, cv in zip(cvars, consts):
            env[var] = cv
            if cv.dtype is not None and cv.dtype == _F64:
                self._f64.setdefault(
                    -id(var), "const f64%s closed over (depth %d)"
                    % (list(cv.shape), depth))
        ivars = list(jaxpr.invars)
        args = list(args)
        keys = list(in_keys or [])
        if len(keys) < len(args):
            keys = [None] * (len(args) - len(keys)) + keys
        if len(args) < len(ivars):
            pad = len(ivars) - len(args)
            args = [_default_for_aval(v.aval)
                    for v in ivars[:pad]] + args
            keys = [None] * pad + keys
        narrowed_vars: Dict[object, int] = {}
        off = len(args) - len(ivars)
        for var, av, key in zip(ivars, args[off:], keys[off:]):
            env[var] = av
            if key is not None:
                narrowed_vars[var] = key

        def key_of(atom) -> Optional[int]:
            if hasattr(atom, "val"):        # Literal: unhashable
                return None
            return narrowed_vars.get(atom)

        for eqn in jaxpr.eqns:
            self.report.n_eqns += 1
            invals = [self._read(env, a) for a in eqn.invars]
            eqn_keys = [key_of(a) for a in eqn.invars]
            # decision-relevance: a previously-narrowed var feeding a
            # comparison (in this body or, via eqn_keys threading,
            # inside a callee) marks its site
            if eqn.primitive.name in _DECISION_PRIMS:
                for key in eqn_keys:
                    if key is not None and key in self._narrow:
                        self._narrow[key].decision_relevant = True
            outs, out_keys = self._apply(eqn, invals, depth, eqn_keys)
            for i, (var, out) in enumerate(zip(eqn.outvars, outs)):
                aval = getattr(var, "aval", None)
                if aval is not None:
                    dt = getattr(aval, "dtype", None)
                    out.dtype = np.dtype(dt) if dt is not None else None
                    out.shape = tuple(getattr(aval, "shape", ()) or ())
                self.report.n_values += 1
                if out.dtype is not None and out.dtype == _F64:
                    self._f64.setdefault(
                        id(eqn), "%s -> f64%s"
                        % (eqn.primitive.name, list(out.shape)))
                if type(var).__name__ != "DropVar":
                    env[var] = out
                    if i < len(out_keys) and out_keys[i] is not None:
                        narrowed_vars[var] = out_keys[i]
            if eqn.primitive.name == "convert_element_type" \
                    and eqn.outvars:
                self._record_convert(eqn, invals[0], depth,
                                     narrowed_vars)
            self._record_structural(eqn, depth)
        return ([self._read(env, a) for a in jaxpr.outvars],
                [key_of(a) for a in jaxpr.outvars])

    # -- records ------------------------------------------------------
    def _record_convert(self, eqn, inval: AbsVal, depth: int,
                        narrowed_vars: Dict[object, int]) -> None:
        new_dt = eqn.params.get("new_dtype")
        if new_dt is None:
            return
        if np.dtype(new_dt) == _F64:
            self._conv64.setdefault(id(eqn), str(eqn))
        src = inval.dtype
        if src is not None and is_narrowing(src, new_dt):
            in_aval = getattr(eqn.invars[0], "aval", None)
            weak = bool(getattr(in_aval, "weak_type", False)) \
                and not tuple(getattr(in_aval, "shape", ()) or ())
            key = id(eqn)
            site = self._narrow.get(key)
            if site is None:
                self._narrow[key] = NarrowSite(
                    src=_dtype_name(src), dst=_dtype_name(new_dt),
                    rng=inval.rng, err=inval.err, depth=depth,
                    weak_src=weak)
            else:
                site.rng = site.rng.join(inval.rng)
                site.err = max(site.err, inval.err)
            narrowed_vars[eqn.outvars[0]] = key

    def _record_structural(self, eqn, depth: int) -> None:
        name = eqn.primitive.name
        if name in HOST_PRIMS:
            self._transfer.setdefault(
                id(eqn), TransferSite(
                    prim=name, depth=depth,
                    benign=(name == "device_put"
                            and _is_alias_device_put(eqn))))
        if name in REPLICATING_PRIMS:
            nbytes = 0
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None:
                    n = 1
                    for d in getattr(aval, "shape", ()) or ():
                        n *= int(d)
                    nbytes += n * np.dtype(aval.dtype).itemsize
            if nbytes >= self.threshold:
                self._repl.setdefault(id(eqn), (name, nbytes, depth))
        ioa = eqn.params.get("input_output_aliases")
        if ioa is not None:
            self._alias.setdefault(id(eqn), (name, tuple(ioa)))

    # -- dispatch -----------------------------------------------------
    def _apply(self, eqn, invals: List[AbsVal], depth: int,
               in_keys: List[Optional[int]]
               ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        name = eqn.primitive.name
        if name == "scan":
            return self._scan(eqn, invals, depth, in_keys)
        if name == "while":
            return self._while(eqn, invals, depth, in_keys)
        if name == "cond":
            return self._cond(eqn, invals, depth, in_keys)
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if sub is not None and (hasattr(sub, "jaxpr")
                                or hasattr(sub, "eqns")):
            return self._call(eqn, sub, invals, depth, in_keys)
        out_avals = [getattr(v, "aval", None) for v in eqn.outvars]
        no_keys: List[Optional[int]] = [None] * len(eqn.outvars)
        rule = _RULES.get(name)
        if rule is not None and out_avals and out_avals[0] is not None:
            try:
                rng, err = rule(eqn, invals, out_avals[0])
            except Exception:       # pragma: no cover - rule robustness
                rng, err = Interval.top(), INF
            outs = [AbsVal(None, (), rng, err)]
            outs += [_default_for_aval(a) for a in out_avals[1:]]
            return outs, no_keys
        if name == "convert_element_type" and invals \
                and out_avals and out_avals[0] is not None:
            return [self._convert(invals[0], out_avals[0])], no_keys
        return ([_default_for_aval(a) if a is not None
                 else AbsVal(None, (), Interval.top(), INF)
                 for a in out_avals], no_keys)

    def _convert(self, a: AbsVal, out_aval) -> AbsVal:
        dt = np.dtype(out_aval.dtype)
        rng, err = a.rng, a.err
        if dt.kind in "iu":
            info = np.iinfo(dt)
            lo = max(min(rng.lo, float(info.max)), float(info.min)) \
                if math.isfinite(rng.lo) else float(info.min)
            hi = min(max(rng.hi, float(info.min)), float(info.max)) \
                if math.isfinite(rng.hi) else float(info.max)
            rng = Interval(math.floor(lo), math.ceil(hi))
            err = 0.0
        elif dt.kind == "f":
            u = _roundoff(dt)
            m = rng.mag()
            err = (a.err + u * m) if math.isfinite(m) else \
                (a.err if u == 0.0 else INF)
        return AbsVal(dt, a.shape, rng, err)

    # -- sub-jaxpr carriers -------------------------------------------
    def _run_closed(self, sub, args: Sequence[AbsVal], depth: int,
                    in_keys: Optional[Sequence[Optional[int]]] = None
                    ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        if hasattr(sub, "jaxpr"):
            consts = [_const_absval(c) for c in sub.consts]
            return self.run(sub.jaxpr, consts, args, depth,
                            in_keys=in_keys)
        return self.run(sub, [], args, depth, in_keys=in_keys)

    def _call(self, eqn, sub, invals, depth, in_keys
              ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        outs, out_keys = self._run_closed(sub, invals, depth,
                                          in_keys=in_keys)
        n = len(eqn.outvars)
        if len(outs) < n:
            outs = outs + [
                _default_for_aval(getattr(v, "aval", None))
                for v in eqn.outvars[len(outs):]]
        out_keys = (list(out_keys) + [None] * n)[:n]
        return outs[:n], out_keys

    def _scan(self, eqn, invals, depth, in_keys
              ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        p = eqn.params
        nc, nk = int(p["num_consts"]), int(p["num_carry"])
        body = p["jaxpr"]
        length = int(p.get("length", 0) or 0)
        consts = invals[:nc]
        carry = list(invals[nc:nc + nk])
        xs = [AbsVal(v.dtype, v.shape[1:] if v.shape else (),
                     v.rng, v.err) for v in invals[nc + nk:]]
        n_ys = len(eqn.outvars) - nk
        ys: Optional[List[AbsVal]] = None
        body_keys = list(in_keys or [None] * len(invals))
        out_keys: List[Optional[int]] = [None] * len(eqn.outvars)

        def step(cur):
            outs, step_keys = self._run_closed(
                body, list(consts) + cur + xs, depth + 1,
                in_keys=body_keys)
            for i, k in enumerate(step_keys[:nk + n_ys]):
                if k is not None:
                    out_keys[i] = k
            return outs[:nk], outs[nk:nk + n_ys]

        if 0 < length <= UNROLL_CAP:
            for _ in range(length):
                carry, step_ys = step(carry)
                ys = step_ys if ys is None else [
                    a.join(b) for a, b in zip(ys, step_ys)]
            self.report.fixpoint = {"rounds": length,
                                    "converged": True,
                                    "widened": False,
                                    "mode": "unrolled"}
        else:
            widened = False
            rounds = 0
            for i in range(FIXPOINT_MAX):
                rounds = i + 1
                new_carry, step_ys = step(carry)
                ys = step_ys if ys is None else [
                    a.join(b) for a, b in zip(ys, step_ys)]
                joined = [c.join(n) for c, n in zip(carry, new_carry)]
                if all(j.rng == c.rng and j.err == c.err
                       for j, c in zip(joined, carry)):
                    self.report.fixpoint = {"rounds": rounds,
                                            "converged": True,
                                            "widened": widened,
                                            "mode": "fixpoint"}
                    break
                if i + 1 >= WIDEN_AFTER:
                    widened = True
                    joined = [
                        AbsVal(c.dtype, c.shape, c.rng.widen(j.rng),
                               j.err if j.err == c.err else INF)
                        for c, j in zip(carry, joined)]
                carry = joined
            else:       # pragma: no cover - widening guarantees exit
                self.report.fixpoint = {"rounds": rounds,
                                        "converged": False,
                                        "widened": widened,
                                        "mode": "fixpoint"}
        ys = ys or []
        return list(carry) + ys, out_keys

    def _while(self, eqn, invals, depth, in_keys
               ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        p = eqn.params
        cn, bn = int(p["cond_nconsts"]), int(p["body_nconsts"])
        cond, body = p["cond_jaxpr"], p["body_jaxpr"]
        cconsts = invals[:cn]
        bconsts = invals[cn:cn + bn]
        carry = list(invals[cn + bn:])
        keys = list(in_keys or [None] * len(invals))
        body_keys = keys[cn:cn + bn] + keys[cn + bn:]
        self._run_closed(cond, list(cconsts) + carry, depth + 1,
                         in_keys=keys[:cn] + keys[cn + bn:])
        widened = False
        for i in range(FIXPOINT_MAX):
            new_carry = self._run_closed(
                body, list(bconsts) + carry, depth + 1,
                in_keys=body_keys)[0][:len(carry)]
            joined = [c.join(n) for c, n in zip(carry, new_carry)]
            if all(j.rng == c.rng and j.err == c.err
                   for j, c in zip(joined, carry)):
                self.report.fixpoint = {"rounds": i + 1,
                                        "converged": True,
                                        "widened": widened,
                                        "mode": "fixpoint"}
                break
            if i + 1 >= WIDEN_AFTER:
                widened = True
                joined = [AbsVal(c.dtype, c.shape, c.rng.widen(j.rng),
                                 j.err if j.err == c.err else INF)
                          for c, j in zip(carry, joined)]
            carry = joined
        return carry, [None] * len(carry)

    def _cond(self, eqn, invals, depth, in_keys
              ) -> Tuple[List[AbsVal], List[Optional[int]]]:
        branches = eqn.params["branches"]
        ops = invals[1:]
        op_keys = list(in_keys or [None] * len(invals))[1:]
        joined: Optional[List[AbsVal]] = None
        out_keys: List[Optional[int]] = [None] * len(eqn.outvars)
        for br in branches:
            outs, br_keys = self._run_closed(br, ops, depth,
                                             in_keys=op_keys)
            for i, k in enumerate(br_keys[:len(out_keys)]):
                if k is not None:
                    out_keys[i] = k
            joined = outs if joined is None else [
                a.join(b) for a, b in zip(joined, outs)]
        return joined or [], out_keys


def interpret(closed, in_ranges: Optional[Dict[int, Tuple[float, float]]]
              = None, in_errs: Optional[Dict[int, float]] = None,
              replicated_threshold: int = 1 << 20) -> DataflowReport:
    """Interpret a ClosedJaxpr abstractly and return the report.

    ``in_ranges`` maps input position -> (lo, hi) from the input
    contract; unmapped float inputs are TOP with error 0 (exact but
    unbounded inputs).  ``in_errs`` optionally seeds per-input error
    bounds (quantized inputs)."""
    report = DataflowReport()
    interp = _Interp(report, replicated_threshold)
    jaxpr = closed.jaxpr
    consts = [_const_absval(c) for c in closed.consts]
    args = []
    for i, var in enumerate(jaxpr.invars):
        av = _default_for_aval(var.aval, err=0.0)
        if in_ranges and i in in_ranges:
            lo, hi = in_ranges[i]
            av.rng = Interval(float(lo), float(hi))
        if in_errs and i in in_errs:
            av.err = float(in_errs[i])
        args.append(av)
    report.out_vals, _ = interp.run(jaxpr, consts, args, 0)
    report.narrowings = list(interp._narrow.values())
    report.transfers = list(interp._transfer.values())
    report.f64_sites = list(interp._f64.values())
    report.f64_converts = list(interp._conv64.values())
    report.replicated_large = list(interp._repl.values())
    report.alias_sites = list(interp._alias.values())
    telemetry.count(C_VALUES, report.n_values, category="analysis")
    return report
