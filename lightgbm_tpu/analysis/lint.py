"""Graft-lint engine: file walking, rule running, suppression, autofix.

Suppression has three layers, in order of preference:

1. fix the finding;
2. inline ``# graftlint: disable=JG00X`` on (or the comment line above)
   the flagged line — for deliberate, locally-justified exceptions;
3. the checked-in baseline file — for grandfathered findings that
   predate the linter. Baseline entries match on (rule, path, stripped
   source line), NOT line numbers, so they survive unrelated edits; a
   baselined line that is fixed or deleted simply stops matching and
   the entry goes stale (``--write-baseline`` re-emits a minimal file).

The gate counts only unsuppressed findings. Telemetry counters under
the ``analysis`` category record findings/suppressed/files per run so
long-lived services that embed the gate surface lint drift in the same
place as their perf counters.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .core import Finding, ModuleContext
from . import rules as rules_pkg

C_FINDINGS = "analysis::findings"
C_SUPPRESSED = "analysis::suppressed"
C_FILES = "analysis::files_scanned"
C_AUTOFIXED = "analysis::autofixed"


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[Tuple[str, str]] = field(default_factory=list)
    autofixed: int = 0

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_dict(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "autofixed": self.autofixed,
            "parse_errors": [{"path": p, "error": e}
                             for p, e in self.parse_errors],
            "findings": [f.to_dict() for f in self.findings],
            "counts": {
                "total": len(self.findings),
                "unsuppressed": len(self.unsuppressed),
                "suppressed": len(self.suppressed),
            },
        }


def iter_py_files(config: GraftlintConfig,
                  paths: Optional[List[str]] = None) -> List[str]:
    """Repo-relative .py paths under the include roots (or `paths`)."""
    roots = paths if paths else config.include
    out: List[str] = []
    for root in roots:
        ap = os.path.join(config.root, root)
        if os.path.isfile(ap) and ap.endswith(".py"):
            rel = os.path.relpath(ap, config.root).replace(os.sep, "/")
            if not config.is_excluded(rel):
                out.append(rel)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      config.root).replace(os.sep, "/")
                if not config.is_excluded(rel):
                    out.append(rel)
    return out


def lint_source(source: str, relpath: str,
                config: Optional[GraftlintConfig] = None,
                rule_ids: Optional[List[str]] = None) -> List[Finding]:
    """Lint one in-memory module (the fixture-test entry point)."""
    config = config or GraftlintConfig()
    ctx = ModuleContext(source, relpath, config)
    if ctx.skip_file:
        return []
    findings: List[Finding] = []
    for rule in rules_pkg.all_rules():
        if rule.id in config.disable:
            continue
        if rule_ids is not None and rule.id not in rule_ids:
            continue
        for f in rule.check(ctx):
            if ctx.is_inline_suppressed(f.rule, f.line):
                f.suppressed = True
                f.suppression = "inline"
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for ent in data.get("findings", []):
        key = (ent["rule"], ent["path"], ent["snippet"])
        out[key] = out.get(key, 0) + int(ent.get("count", 1))
    return out


def apply_baseline(findings: List[Finding],
                   baseline: Dict[Tuple[str, str, str], int]) -> None:
    budget = dict(baseline)
    for f in findings:
        if f.suppressed:
            continue
        left = budget.get(f.key(), 0)
        if left > 0:
            budget[f.key()] = left - 1
            f.suppressed = True
            f.suppression = "baseline"


def write_baseline(findings: List[Finding], path: str) -> int:
    """Emit a minimal baseline covering every currently-unsuppressed
    finding (inline-suppressed ones stay inline). Returns entry count."""
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        if f.suppression == "inline":
            continue
        counts[f.key()] = counts.get(f.key(), 0) + 1
    ents = [{"rule": r, "path": p, "snippet": s, "count": c}
            for (r, p, s), c in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "comment": "grandfathered graft-lint findings; matched "
                              "by (rule, path, source line), not line "
                              "numbers. Shrink this file, never grow it.",
                   "findings": ents}, f, indent=1)
        f.write("\n")
    return len(ents)


def prune_baseline(findings: List[Finding], path: str) -> Tuple[int, int]:
    """Drop baseline entries whose (rule, path, source line) no longer
    matches any CURRENT finding, clamping counts to the matched number.
    Returns (kept, dropped) entry-count deltas. A stale entry is a free
    suppression waiting for a regression to hide under — fixing the
    grandfathered finding must shrink the file, and ``--prune-baseline``
    makes that mechanical instead of manual."""
    if not os.path.isfile(path):
        return 0, 0
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    current: Dict[Tuple[str, str, str], int] = {}
    for fnd in findings:
        key = fnd.key()
        current[key] = current.get(key, 0) + 1
    kept, dropped = [], 0
    for ent in data.get("findings", []):
        key = (ent["rule"], ent["path"], ent["snippet"])
        have = current.get(key, 0)
        want = int(ent.get("count", 1))
        if have <= 0:
            dropped += want
            continue
        if have < want:
            dropped += want - have
            ent = dict(ent, count=have)
        kept.append(ent)
    if dropped:
        data["findings"] = kept
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1)
            f.write("\n")
    return len(kept), dropped


# ---------------------------------------------------------------------------
# autofix
# ---------------------------------------------------------------------------

def apply_fixes(findings: List[Finding], config: GraftlintConfig) -> int:
    """Apply textual fixes bottom-up per file; returns fixes applied."""
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        if f.fix is not None and not f.suppressed:
            by_path.setdefault(f.path, []).append(f)
    applied = 0
    for relpath, fs in by_path.items():
        ap = os.path.join(config.root, relpath)
        with open(ap, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines(keepends=True)
        fs.sort(key=lambda f: f.fix[1][0], reverse=True)
        seen_spans = set()
        for f in fs:
            kind, (lo, hi, new_text) = f.fix
            assert kind == "replace_span", kind
            if (lo, hi) in seen_spans:      # one fix per statement
                continue
            seen_spans.add((lo, hi))
            repl = [] if new_text is None else [new_text + "\n"]
            lines[lo - 1:hi] = repl
            applied += 1
        with open(ap, "w", encoding="utf-8") as fh:
            fh.write("".join(lines))
    return applied


# ---------------------------------------------------------------------------
# top-level run
# ---------------------------------------------------------------------------

def run_lint(paths: Optional[List[str]] = None,
             config: Optional[GraftlintConfig] = None,
             rule_ids: Optional[List[str]] = None,
             use_baseline: bool = True,
             autofix: bool = False) -> LintReport:
    """Lint the repo (or `paths`); the CLI and the self-scan test both
    land here. With `autofix`, fixable findings are applied and the
    affected files re-linted so the report reflects the fixed tree."""
    config = config or load_config()
    report = LintReport()
    relpaths = iter_py_files(config, paths)
    for rel in relpaths:
        ap = os.path.join(config.root, rel)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                src = f.read()
            report.findings.extend(
                lint_source(src, rel, config, rule_ids))
        except SyntaxError as e:
            report.parse_errors.append((rel, str(e)))
    report.files_scanned = len(relpaths)
    if use_baseline:
        apply_baseline(report.findings,
                       load_baseline(config.baseline_path()))
    if autofix:
        report.autofixed = apply_fixes(report.findings, config)
        if report.autofixed:
            fixed_paths = sorted({f.path for f in report.findings
                                  if f.fix is not None})
            report.findings = [f for f in report.findings
                               if f.path not in fixed_paths]
            for rel in fixed_paths:
                ap = os.path.join(config.root, rel)
                with open(ap, "r", encoding="utf-8") as f:
                    src = f.read()
                report.findings.extend(
                    lint_source(src, rel, config, rule_ids))
            if use_baseline:
                for f in report.findings:
                    f.suppressed = False if f.suppression == "baseline" \
                        else f.suppressed
                    if f.suppression == "baseline":
                        f.suppression = ""
                apply_baseline(report.findings,
                               load_baseline(config.baseline_path()))
            report.findings.sort(
                key=lambda f: (f.path, f.line, f.col, f.rule))
    telemetry.count(C_FILES, report.files_scanned, category="analysis")
    telemetry.count(C_FINDINGS, len(report.unsuppressed),
                    category="analysis")
    telemetry.count(C_SUPPRESSED, len(report.suppressed),
                    category="analysis")
    if report.autofixed:
        telemetry.count(C_AUTOFIXED, report.autofixed, category="analysis")
    return report
