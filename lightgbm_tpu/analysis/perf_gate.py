"""Perf-regression sentinel: trajectory verdicts over the bench rounds.

Every archived ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` is a recorded
measurement; nothing so far *interpreted* them — the stack could record
a p99 yet could not say "this round is slower than the last one". This
auditor closes that loop behind the existing gate
(``python -m lightgbm_tpu.analysis --perf [--json]``):

* **schema validation** — every round parses through
  :func:`load_round`, which raises :class:`RoundError` with a clear
  message (round name + what is wrong) instead of a ``KeyError``
  mid-series. Rounds from index :data:`REQUIRE_META_FROM` on MUST carry
  the self-describing ``meta`` block bench.py stamps (schema version,
  git SHA, device profile, jax version, BENCH_* knobs, repeats +
  per-key spread); earlier rounds are grandfathered as ``legacy``.
* **trajectory verdicts** — per-key series over the whole round
  sequence, compared **within a lineage**: rounds are comparable only
  when their device + workload-knob fingerprint matches (a round
  recorded on a CPU box must not "regress" a TPU round — it opens a
  new lineage instead, which the report names). The latest round of
  each lineage is checked against its predecessor; a headline key
  moving against its direction by more than the noise band FAILS the
  gate, improvements are reported, within-band moves pass.
* **noise bands** — a key's band is the larger of the recorded
  relative spread from ``BENCH_REPEATS`` median-of-k runs (both
  rounds' ``meta.spread``) and the configured floor
  (``[tool.graftlint] perf-band``, default 0.15).
* **coverage** — headline keys the north-star trajectory is built on
  (:data:`EXPECTED_KEYS`) absent from EVERY round is exactly the
  stale-trajectory state ROADMAP item 1 opens with; the sentinel fails
  and names them rather than passing silently. The multichip series
  gates on the latest round's ``ok``.

``tables()`` ships the full trajectories + verdicts (and the roofline
cards when a phase snapshot is archived next to the rounds) as the
``--json`` ``perf_tables`` payload.
"""
from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .jaxpr_audit import AuditResult

C_ROUNDS = "analysis::perf_rounds"
C_REGRESSED = "analysis::perf_regressed"
C_MISSING = "analysis::perf_missing_keys"

SCHEMA_VERSION = 1
# rounds r01..r05 predate the meta block; everything after must carry it
REQUIRE_META_FROM = 6

# headline keys: direction tells the sentinel what "worse" means
HIGHER_BETTER = (
    "value", "vs_baseline", "ranking_value", "ranking_vs_baseline",
    "expo_value", "expo_vs_baseline", "expo_level_value",
    "expo_level_vs_baseline", "allstate_value", "allstate_vs_baseline",
    "yahoo_value", "yahoo_vs_baseline", "voting_value",
    "voting_vs_baseline", "predict_value", "predict_expo_value",
    # split-margin p01 (numerics::split_margin, telemetry/health): a
    # quantization PR that collapses decision margins gates here even
    # when throughput holds — the runtime twin of the quant_certify
    # SPLIT_DECISION_BUDGET
    "margin_p01",
    # quantized-collective payload reduction (ROADMAP item 2): estimated
    # full-width bytes over estimated shipped bytes for the histogram
    # exchanges — the int16 + PV-Tree voting compression the acceptance
    # criterion pins at >= 3x
    "hist_compress_ratio",
    # async serving (serving/): sustained open-loop throughput through
    # the continuous-batching server, and its ratio over the
    # synchronous BatchServer at the same request mix (the acceptance
    # criterion pins >= 2x)
    "serving_rps", "serving_vs_sync",
    # multi-model sweeps (multimodel/): models trained per wall-second
    # through the vmapped fused iteration — the whole point of batching
    # the model axis is that this scales past 1/t_serial
    "models_per_sec",
)
LOWER_BETTER = (
    "predict_p50", "predict_p99", "checkpoint_overhead_frac",
    "expo_level_launches_per_tree",
    # fused boosting iteration (PR 17): device launches per boosting
    # iteration (tree_learner::iter_launches / iters) — the fusion
    # target the whole-iteration program exists to shrink (gbdt lands
    # at 1/k for k-iteration scan batches)
    "launches_per_iter",
    # estimated histogram-exchange bytes actually shipped per run
    # (collective::dcn_hist_bytes) — the payload the quantized wire
    # format exists to shrink
    "dcn_hist_bytes",
    # voting: fraction of features whose planes cross the wire
    # (2*top_k/F) — the PV-Tree pre-selection ratio
    "reduced_feature_frac",
    # serving rounds verdict automatically on the SLO keys: open-loop
    # mean queue depth (load proxy) and the fraction of requests whose
    # arrival->answer latency blew the deadline budget
    "predict_qdepth", "serving_deadline_miss_frac",
    # programs compiled by the WARM sweep call (tree_learner::mm_programs
    # counter delta): the bucket ladder exists so this stays 0 — any
    # growth means a sweep shape started recompiling
    "sweep_compiles",
)
# headline keys whose PRESENCE depends on a measurement-only knob
# (margin_p01 only exists when BENCH_TELEMETRY recorded the margin
# histogram — and measurement-only knobs are deliberately excluded from
# the lineage fingerprint): these still direction-gate when two rounds
# both carry them, but vanishing is a recording-mode change, not a
# phase crash, so the vanish-gate skips them
MEASUREMENT_CONDITIONAL = ("margin_p01",
                           # the wire-byte keys read telemetry counters
                           # (bench run_voting -> counts_snapshot): a
                           # BENCH_TELEMETRY=0 round omits them without
                           # the phase having crashed
                           "dcn_hist_bytes", "hist_compress_ratio",
                           # queue depth exists only when the open-loop
                           # phases run (BENCH_SKIP_PREDICT/SERVING
                           # skip them without a crash)
                           "predict_qdepth",
                           # launch accounting reads the telemetry
                           # counter snapshot, so a BENCH_TELEMETRY=0
                           # round omits it without the phase crashing
                           "launches_per_iter",
                           # compile accounting for the sweep phase reads
                           # the same counter snapshot (BENCH_SKIP_SWEEP /
                           # BENCH_TELEMETRY=0 rounds omit it)
                           "sweep_compiles")

# per-key minimum noise bands: bucket-quantized keys can only move in
# layout-growth steps. margin_p01 is a quantile of the 2.0-growth
# split-margin histogram (telemetry/health), so one benign bucket-edge
# hop reads as a ±50% move — far outside the default 15% band. 0.6
# lets a single edge hop pass while a genuine collapse (the 100x
# failure mode the key exists for) still gates.
KEY_BAND_FLOOR = {"margin_p01": 0.6}

# informational keys (counts, sizes) are tracked but never gate
# the north-star trajectory keys: absent from EVERY round = the stale
# state the gate must name loudly (ROADMAP item 1)
EXPECTED_KEYS = ("value", "ranking_value", "expo_value",
                 "expo_level_value")

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
_MULTICHIP_RE = re.compile(r"MULTICHIP_r(\d+)\.json$")
_META_REQUIRED = ("schema", "device", "jax")


class RoundError(Exception):
    """A bench round file the sentinel cannot interpret (malformed
    envelope, missing meta on a post-legacy round, wrong types)."""


@dataclass
class Round:
    """One validated BENCH_r*.json."""

    index: int
    path: str
    parsed: Dict[str, object]
    meta: Optional[dict] = None
    legacy: bool = False

    @property
    def spread(self) -> Dict[str, float]:
        if not self.meta:
            return {}
        return {k: float(v)
                for k, v in (self.meta.get("spread") or {}).items()}

    def fingerprint(self) -> str:
        """Comparability lineage: device + workload knobs. Meta-less
        rounds share the single ``legacy`` lineage (they were recorded
        on the same driver box with default knobs). Measurement-only
        knobs (repeat count, telemetry opt-out, output paths, phase
        skips) do NOT change what is being measured, so they stay out
        of the fingerprint — flipping BENCH_REPEATS on must not sever
        the lineage the spread mechanism exists to serve."""
        if not self.meta:
            return "legacy"
        dev = self.meta.get("device") or {}
        knobs = self.meta.get("knobs") or {}
        sized = ";".join(
            "%s=%s" % (k, knobs[k]) for k in sorted(knobs)
            if not (str(k).endswith("_OUT")
                    or str(k).startswith("BENCH_SKIP_")
                    or k in ("BENCH_REPEATS", "BENCH_TELEMETRY")))
        return "%s|%s" % (dev.get("kind", dev.get("name", "?")), sized)


def validate_round(payload: object, name: str, index: int) -> Round:
    """Envelope + meta validation with clear errors (never KeyError)."""
    if not isinstance(payload, dict):
        raise RoundError("%s: round json must be an object, got %s"
                         % (name, type(payload).__name__))
    parsed = payload.get("parsed")
    if not isinstance(parsed, dict):
        raise RoundError("%s: missing or non-object 'parsed' block "
                         "(the bench metric line)" % name)
    meta = payload.get("meta")
    if meta is None and isinstance(parsed.get("meta"), dict):
        # bench.py stamps meta INTO its printed metric line; the driver
        # envelope archives that line under 'parsed'
        meta = parsed["meta"]
    if meta is not None:
        if not isinstance(meta, dict):
            raise RoundError("%s: 'meta' must be an object, got %s"
                             % (name, type(meta).__name__))
        missing = [k for k in _META_REQUIRED if k not in meta]
        if missing:
            raise RoundError("%s: meta block is missing %s (a "
                             "self-describing round records schema/"
                             "device/jax — re-record with the current "
                             "bench.py)" % (name, ", ".join(missing)))
    elif index >= REQUIRE_META_FROM:
        raise RoundError("%s: rounds from r%02d on must carry the "
                         "self-describing 'meta' block (schema version, "
                         "device, knobs); meta-less rounds are only "
                         "grandfathered up to r%02d"
                         % (name, REQUIRE_META_FROM,
                            REQUIRE_META_FROM - 1))
    return Round(index=index, path=name, parsed=parsed, meta=meta,
                 legacy=meta is None)


def load_round(path: str) -> Round:
    name = os.path.basename(path)
    m = _ROUND_RE.search(name)
    if not m:
        raise RoundError("%s: not a BENCH_r<NN>.json round file" % name)
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        raise RoundError("%s: unreadable round json (%s)" % (name, exc))
    return validate_round(payload, name, int(m.group(1)))


def discover_rounds(root: str) -> Tuple[List[Round], List[dict],
                                        List[str]]:
    """(bench rounds sorted by index, multichip rounds, errors)."""
    rounds: List[Round] = []
    errors: List[str] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        if not _ROUND_RE.search(os.path.basename(path)):
            continue
        try:
            rounds.append(load_round(path))
        except RoundError as exc:
            errors.append(str(exc))
    multichip: List[dict] = []
    for path in sorted(glob.glob(os.path.join(root,
                                              "MULTICHIP_r*.json"))):
        m = _MULTICHIP_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
            if not isinstance(payload, dict):
                raise ValueError("not an object")
        except (OSError, ValueError) as exc:
            errors.append("%s: unreadable multichip round (%s)"
                          % (os.path.basename(path), exc))
            continue
        payload = dict(payload, index=int(m.group(1)))
        multichip.append(payload)
    rounds.sort(key=lambda r: r.index)
    multichip.sort(key=lambda d: d["index"])
    return rounds, multichip, errors


def _numeric_keys(parsed: Dict[str, object]) -> Dict[str, float]:
    out = {}
    for k, v in parsed.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[k] = float(v)
    return out


@dataclass
class Verdict:
    """One headline key's latest-vs-predecessor comparison."""

    key: str
    status: str               # ok | improved | REGRESSED | new | missing
    round: int                # the round being judged (latest of lineage)
    prev_round: Optional[int] = None
    value: Optional[float] = None
    prev_value: Optional[float] = None
    change: Optional[float] = None     # relative, signed (+ = better)
    band: Optional[float] = None
    note: str = ""

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


@dataclass
class PerfReport:
    rounds: List[Round] = field(default_factory=list)
    multichip: List[dict] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    verdicts: List[Verdict] = field(default_factory=list)
    missing_keys: List[str] = field(default_factory=list)
    lineages: Dict[str, List[int]] = field(default_factory=dict)
    root: str = ""          # where the rounds were discovered
    band: float = 0.15      # the band floor this report was judged at

    @property
    def regressions(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.status == "REGRESSED"]

    @property
    def improvements(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.status == "improved"]


def evaluate(rounds: List[Round], band_floor: float,
             multichip: Optional[List[dict]] = None,
             errors: Optional[List[str]] = None) -> PerfReport:
    """The sentinel core: pure function of the validated round series
    (the fixture tests drive exactly this)."""
    rep = PerfReport(rounds=rounds, multichip=multichip or [],
                     errors=list(errors or []), band=band_floor)
    for r in rounds:
        rep.lineages.setdefault(r.fingerprint(), []).append(r.index)

    # coverage: the north-star keys must exist SOMEWHERE in the series
    seen_keys = set()
    for r in rounds:
        seen_keys.update(_numeric_keys(r.parsed))
    rep.missing_keys = [k for k in EXPECTED_KEYS if k not in seen_keys]

    # latest-vs-predecessor within each lineage
    by_lineage: Dict[str, List[Round]] = {}
    for r in rounds:
        by_lineage.setdefault(r.fingerprint(), []).append(r)
    for lineage, series in by_lineage.items():
        if not series:
            continue
        latest = series[-1]
        latest_vals = _numeric_keys(latest.parsed)
        vals_by_round = [(r, _numeric_keys(r.parsed))
                         for r in series[:-1]]
        for key in HIGHER_BETTER + LOWER_BETTER:
            # the predecessor is the LAST earlier round of this lineage
            # that actually carried the key — so a key that vanished
            # keeps gating on every subsequent round (not just the
            # first one after the crash), and a key skipping one round
            # still compares against its real previous measurement
            prev = None
            prev_vals: Dict[str, float] = {}
            for r, vals in reversed(vals_by_round):
                if key in vals:
                    prev, prev_vals = r, vals
                    break
            if key not in latest_vals and prev is None:
                continue
            if key not in latest_vals and key in MEASUREMENT_CONDITIONAL:
                # recorded under a telemetry-on round, absent now: a
                # measurement-mode flip, not a crashed phase
                continue
            if key not in latest_vals:
                rep.verdicts.append(Verdict(
                    key=key, status="missing", round=latest.index,
                    prev_round=prev.index,
                    prev_value=prev_vals.get(key),
                    note="recorded in r%02d but absent from the latest "
                         "round of this lineage (did the phase crash?)"
                         % prev.index))
                continue
            if prev is None:
                rep.verdicts.append(Verdict(
                    key=key, status="new", round=latest.index,
                    value=latest_vals[key],
                    note="first round of lineage %r carrying this key"
                         % lineage))
                continue
            new_v, old_v = latest_vals[key], prev_vals[key]
            band = max(band_floor,
                       KEY_BAND_FLOOR.get(key, 0.0),
                       latest.spread.get(key, 0.0),
                       prev.spread.get(key, 0.0))
            higher_better = key in HIGHER_BETTER
            denom = max(abs(old_v), 1e-12)
            rel = (new_v - old_v) / denom
            better = rel if higher_better else -rel
            status = ("REGRESSED" if better < -band
                      else "improved" if better > band else "ok")
            rep.verdicts.append(Verdict(
                key=key, status=status, round=latest.index,
                prev_round=prev.index, value=new_v, prev_value=old_v,
                change=round(better, 4), band=round(band, 4)))
    return rep


def _resolve_rounds(config: Optional[GraftlintConfig]) -> PerfReport:
    config = config or load_config()
    root = os.environ.get("LGBTPU_PERF_ROUNDS_DIR") or config.root
    band = float(getattr(config, "perf_band", 0.15))
    rounds, multichip, errors = discover_rounds(root)
    rep = evaluate(rounds, band, multichip=multichip, errors=errors)
    rep.root = root
    return rep


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    """Gate entry point (CLI ``--perf``): three AuditResults —
    round schema health, the trajectory verdict, multichip health."""
    rep = artifact if isinstance(artifact, PerfReport) \
        else _resolve_rounds(config)
    telemetry.count(C_ROUNDS, len(rep.rounds), category="analysis")
    out: List[AuditResult] = []

    n_meta = sum(1 for r in rep.rounds if not r.legacy)
    no_rounds = not rep.rounds and not rep.errors
    if no_rounds:
        # a directory with ZERO BENCH_r* rounds is a RoundError-class
        # state, reported cleanly instead of passing silently (or
        # worse, tracebacking): gate mode (--perf) exits 1 — a bench
        # refresh asked the sentinel to judge nothing — while the
        # pre-commit advisory mode reports and still exits 0
        detail = ("no BENCH_r* rounds recorded%s — record a round "
                  "with bench.py (or point LGBTPU_PERF_ROUNDS_DIR at "
                  "the archive); the pre-commit hook runs this in "
                  "--perf-advisory mode, which never blocks"
                  % (" under %s" % rep.root if rep.root else ""))
        out.append(AuditResult(name="perf_rounds", ok=False,
                               detail=detail))
        out.append(AuditResult(name="perf_trajectory", ok=True,
                               detail="no bench rounds to judge",
                               skipped=True))
        # a multichip-only archive still gets its series judged: the
        # zero-BENCH-rounds failure must not swallow the one verdict
        # the directory CAN support
        out.extend(_multichip_result(rep))
        return out
    out.append(AuditResult(
        name="perf_rounds",
        ok=not rep.errors,
        detail=("%d bench round(s) parsed (%d self-describing, %d "
                "legacy), %d multichip"
                % (len(rep.rounds), n_meta,
                   len(rep.rounds) - n_meta, len(rep.multichip)))
        if not rep.errors else "; ".join(rep.errors[:3])))

    if not rep.rounds:
        # every BENCH round failed to parse: the errors gate above,
        # but the multichip series (if any) still gets its verdict
        out.append(AuditResult(name="perf_trajectory", ok=True,
                               detail="no bench rounds to judge",
                               skipped=True))
        out.extend(_multichip_result(rep))
        return out

    if rep.regressions:
        telemetry.count(C_REGRESSED, len(rep.regressions),
                        category="analysis")
    if rep.missing_keys:
        telemetry.count(C_MISSING, len(rep.missing_keys),
                        category="analysis")
    bad_bits = []
    for v in rep.regressions:
        bad_bits.append("%s r%02d %.4g -> r%02d %.4g (%.1f%% worse, "
                        "band %.0f%%)"
                        % (v.key, v.prev_round, v.prev_value, v.round,
                           v.value, -100.0 * v.change, 100.0 * v.band))
    for v in rep.verdicts:
        # a headline key the lineage used to record but the LATEST
        # round lacks usually means the phase crashed (bench.py catches
        # per-phase failures and keeps going) — that must gate, not
        # pass silently
        if v.status == "missing":
            bad_bits.append("%s vanished from r%02d (recorded in "
                            "r%02d — did the phase crash?)"
                            % (v.key, v.round, v.prev_round))
    if rep.missing_keys:
        bad_bits.append("trajectory keys never recorded in ANY round: "
                        + ", ".join(rep.missing_keys)
                        + " (record a bench round with the level path "
                          "engaged)")
    ok_detail = ("%d verdict(s) across %d lineage(s): %d improved, %d "
                 "within band, %d new"
                 % (len(rep.verdicts), len(rep.lineages),
                    len(rep.improvements),
                    sum(1 for v in rep.verdicts if v.status == "ok"),
                    sum(1 for v in rep.verdicts if v.status == "new")))
    out.append(AuditResult(
        name="perf_trajectory",
        ok=not bad_bits,
        detail="; ".join(bad_bits[:4]) if bad_bits else ok_detail))

    out.extend(_multichip_result(rep))
    return out


def _multichip_result(rep: PerfReport) -> List[AuditResult]:
    if not rep.multichip:
        return []
    latest = rep.multichip[-1]
    mc_ok = bool(latest.get("ok")) and latest.get("rc", 1) == 0
    # multichip rounds carrying a `parsed` block (MULTICHIP_r07 on:
    # dcn_hist_bytes / hist_compress_ratio / reduced_feature_frac from
    # the quantized+voting dry run) direction-gate latest-vs-predecessor
    # exactly like the bench headline keys — the payload-reduction
    # trajectory is guarded from the round that first recorded it
    bad: List[str] = []
    latest_vals = (_numeric_keys(latest["parsed"])
                   if isinstance(latest.get("parsed"), dict) else {})
    prev_vals: Dict[str, float] = {}
    prev_idx = None
    for m in rep.multichip[:-1]:
        if isinstance(m.get("parsed"), dict):
            prev_vals = _numeric_keys(m["parsed"])
            prev_idx = m["index"]
    for key in HIGHER_BETTER + LOWER_BETTER:
        if key not in latest_vals or key not in prev_vals:
            continue
        new_v, old_v = latest_vals[key], prev_vals[key]
        # the same band floor the bench headline keys were judged at
        # (plus any per-key bucket-quantization floor)
        band = max(rep.band, KEY_BAND_FLOOR.get(key, 0.0))
        rel = (new_v - old_v) / max(abs(old_v), 1e-12)
        better = rel if key in HIGHER_BETTER else -rel
        if better < -band:
            bad.append("%s r%02d %.4g -> r%02d %.4g (%.1f%% worse)"
                       % (key, prev_idx, old_v, latest["index"], new_v,
                          -100.0 * better))
    detail = ("latest multichip round r%02d: %s devices, ok=%s"
              % (latest["index"], latest.get("n_devices", "?"),
                 latest.get("ok")))
    if latest_vals:
        detail += "; %d payload key(s) tracked" % len(latest_vals)
    if bad:
        detail = "; ".join(bad)
    return [AuditResult(name="perf_multichip", ok=mc_ok and not bad,
                        detail=detail)]


def check_fixture(payload) -> List[str]:
    """Uniform fixture hook: failures for a synthetic round series
    (list of {index, parsed[, meta]} dicts [+ {'band': x} config])."""
    band = 0.15
    rounds: List[Round] = []
    for item in payload:
        if "band" in item and "parsed" not in item:
            band = float(item["band"])
            continue
        rounds.append(validate_round(
            {"parsed": item["parsed"], "meta": item.get("meta")},
            "BENCH_r%02d.json" % item["index"], item["index"]))
    rep = evaluate(rounds, band)
    out = ["%s: r%02d %.4g -> r%02d %.4g beyond band"
           % (v.key, v.prev_round, v.prev_value, v.round, v.value)
           for v in rep.regressions]
    out.extend("%s vanished from r%02d" % (v.key, v.round)
               for v in rep.verdicts if v.status == "missing")
    out.extend("missing: %s" % k for k in rep.missing_keys)
    return out


def _load_phase_snaps(root: str) -> Tuple[Optional[dict], Optional[str]]:
    """The newest archived bench phase snapshot next to the rounds
    (shared discovery policy: telemetry/perfmodel.find_phase_snapshot)."""
    from ..telemetry.perfmodel import find_phase_snapshot
    path = find_phase_snapshot(root)
    if path is None:
        return None, None
    try:
        with open(path, "r", encoding="utf-8") as f:
            snaps = json.load(f)
        return (snaps if isinstance(snaps, dict) else None), path
    except (OSError, ValueError):
        return None, path


def tables(config: Optional[GraftlintConfig] = None,
           artifact=None) -> dict:
    """The ``--json`` ``perf_tables`` payload: round summaries, per-key
    trajectories, verdicts, multichip series, and the roofline cards
    computed from the newest archived phase snapshot."""
    config = config or load_config()
    if isinstance(artifact, PerfReport):
        rep = artifact
        root = os.environ.get("LGBTPU_PERF_ROUNDS_DIR") or config.root
    else:
        rep = _resolve_rounds(config)
        root = rep.root
    traj: Dict[str, List[dict]] = {}
    for r in rep.rounds:
        for k, v in _numeric_keys(r.parsed).items():
            traj.setdefault(k, []).append(
                {"round": r.index, "value": v,
                 "lineage": r.fingerprint()})
    payload = {
        "rounds": [{"index": r.index, "path": os.path.basename(r.path),
                    "legacy": r.legacy, "lineage": r.fingerprint(),
                    "meta": r.meta}
                   for r in rep.rounds],
        "errors": rep.errors,
        "lineages": rep.lineages,
        "trajectories": traj,
        "verdicts": [v.to_dict() for v in rep.verdicts],
        "missing_keys": rep.missing_keys,
        "multichip": [{"index": m["index"], "ok": m.get("ok"),
                       "rc": m.get("rc"),
                       "n_devices": m.get("n_devices")}
                      for m in rep.multichip],
    }
    snaps, snap_path = _load_phase_snaps(root)
    if snaps:
        from ..telemetry import perfmodel
        from ..telemetry.devices import get_profile
        name = getattr(config, "audit_device", "v5e")
        profile = None if name == "auto" else get_profile(name)
        cards = []
        for phase_key, shape_name in perfmodel.PHASE_SHAPES.items():
            snap = snaps.get(phase_key)
            if not isinstance(snap, dict):
                continue
            if isinstance(snap.get("perf_card"), dict):
                # archived at record time, on the RECORDING device's
                # profile — more honest than recomputing against the
                # configured audit device
                cards.append(snap["perf_card"])
            else:
                cards.append(perfmodel.report_card(
                    snap, shape_name, profile=profile).to_dict())
        payload["roofline"] = {"snapshot": os.path.basename(snap_path),
                               "cards": cards}
    else:
        payload["roofline"] = {"snapshot": None, "cards": []}
    return payload


def render_report(rep: PerfReport) -> str:
    """Human-readable sentinel report (CLI text mode)."""
    lines = ["perf sentinel: %d round(s), %d lineage(s)"
             % (len(rep.rounds), len(rep.lineages))]
    for lineage, idxs in sorted(rep.lineages.items()):
        lines.append("  lineage %-40s rounds %s"
                     % (lineage[:40],
                        ",".join("r%02d" % i for i in idxs)))
    for v in rep.verdicts:
        if v.status == "new":
            lines.append("  %-32s r%02d %-10.4g NEW (%s)"
                         % (v.key, v.round, v.value, v.note))
        elif v.status == "missing":
            lines.append("  %-32s r%02d MISSING (%s)"
                         % (v.key, v.round, v.note))
        else:
            lines.append("  %-32s r%02d %-10.4g -> r%02d %-10.4g "
                         "%+6.1f%% (band %.0f%%) %s"
                         % (v.key, v.prev_round, v.prev_value, v.round,
                            v.value, 100.0 * v.change, 100.0 * v.band,
                            v.status))
    for k in rep.missing_keys:
        lines.append("  !! %s never recorded in any round (stale "
                     "trajectory)" % k)
    for e in rep.errors:
        lines.append("  !! %s" % e)
    return "\n".join(lines)
