"""Collective-order auditor: rank-consistent DCN collective sequences.

The distributed drivers (``parallel/multihost.py``,
``parallel/distributed.py``) and the resilience resume path issue
host-side collectives (allgather/allreduce/broadcast/barrier over DCN)
that every rank must reach in the SAME order: a collective that one
rank executes and another skips deadlocks the pod until the retry
guard's deadline fires — on an unguarded call site, forever. The
classic way to write that bug is a branch on a rank-dependent value::

    if rank == 0:
        stats = process_allgather(local)    # ranks 1..n never arrive

This module walks the distributed modules symbolically (AST only — no
network, no devices) and extracts each module's abstract collective
trace: op kind, call site, the guard label where it is a constant, and
a payload snippet where derivable. It then verifies rank-consistency:

* a collective under an ``if``/``while``/``for`` whose condition (or
  iteration space) derives from a rank-dependent value is a finding,
  UNLESS the two branches of the ``if`` issue identical collective
  sequences (both-branch symmetry is fine — the ranks still agree);
* an early exit (``return``/``raise``/``break``/``continue``) inside a
  rank-dependent branch with collectives still ahead in the function is
  the same deadlock one hop removed, and is flagged too;
* every DCN collective call site must be wrapped by the
  ``resilience/retry.py`` guard (the per-file lint form of this is rule
  JG009; the audit reports the whole-program count);
* every collective site must also RECORD TELEMETRY (the
  ``collective_observed`` audit): an unobserved collective is invisible
  to the latency/bytes histograms the pod-scale rewrite measures
  against. A guarded site is observed by construction — ``guard`` itself
  records op-kind histograms, a fact :func:`guard_records_telemetry`
  proves by parsing ``resilience/retry.py`` — and a direct site counts
  only under an explicit ``telemetry.scope`` / ``@telemetry.timed``.

Rank-dependence is a small intra-function taint analysis: parameters
and locals named like a rank (``rank``, ``process_id``, …), values of
``jax.process_index()``, and anything assigned from an expression that
mentions one of those. Uniform quantities (``world``,
``process_count()``, config values) are deliberately NOT tainted —
every rank computes them identically, so branching on them is safe.

The trace (``extract_repo_trace``) rides the CLI's ``--json`` payload,
so the item-2 collectives rewrite can diff its before/after collective
order the way BENCH files diff throughput.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .core import ModuleContext
from .jaxpr_audit import AuditResult

C_SITES = "analysis::collective_sites"
C_DIVERGENT = "analysis::collective_divergent"
C_UNGUARDED = "analysis::collective_unguarded"
C_UNOBSERVED = "analysis::collective_unobserved"

# host-side DCN collectives (jax.experimental.multihost_utils): matched
# by final attribute so both the dotted module form and a bare import
# resolve. In-program mesh collectives (psum/all_gather inside jitted
# growers) are XLA's to sequence and are out of scope here.
COLLECTIVE_KINDS: Dict[str, str] = {
    "process_allgather": "allgather",
    "process_allgather_tree": "allgather",
    "broadcast_one_to_all": "broadcast",
    "sync_global_devices": "barrier",
    "assert_equal": "barrier",
}

# in-program mesh-collective WRAPPERS (ops/quantize.py): called inside
# jitted growers with a literal label as the first argument. They are
# traced into the `mesh_sites` section of the collective trace — the
# wire-format diff artifact for the quantized-histogram exchange — but
# stay OUT of the host-side order/guard/observed audits (XLA sequences
# in-program collectives; the retry guard wraps only host DCN calls).
MESH_WRAPPERS: Dict[str, str] = {
    "plane_psum": "psum",
    "vote_allgather": "allgather",
}

# names that ARE a rank on sight; everything else only becomes tainted
# by assignment from one of these
_RANK_NAMES = {"rank", "process_id", "process_index", "rank_id",
               "local_rank"}
_RANK_CALLS = ("process_index",)

_EXITS = (ast.Return, ast.Raise, ast.Break, ast.Continue)


@dataclass
class CollectiveSite:
    """One abstract collective call site in a module's trace."""

    kind: str                  # allgather | allreduce | broadcast | ...
    path: str
    line: int
    func: str                  # enclosing function qualname ("" = module)
    name: str = ""             # guard label when a constant string
    payload: str = ""          # source snippet of the payload arg
    guarded: bool = False      # wrapped by resilience_retry.guard
    observed: bool = False     # records telemetry (span or histogram)
    mesh: bool = False         # in-program mesh collective (MESH_WRAPPERS)
    conditions: Tuple[str, ...] = ()   # enclosing rank-dependent tests
    node: Optional[ast.AST] = field(default=None, repr=False, compare=False)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "path": self.path, "line": self.line,
                "func": self.func, "name": self.name,
                "payload": self.payload, "guarded": self.guarded,
                "observed": self.observed, "mesh": self.mesh,
                "rank_dependent": bool(self.conditions),
                "conditions": list(self.conditions)}


@dataclass
class CollectiveFinding:
    """One rank-divergence hazard."""

    path: str
    line: int
    func: str
    message: str

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "func": self.func,
                "message": self.message}


def _snippet(src: str, node: Optional[ast.AST], limit: int = 60) -> str:
    if node is None:
        return ""
    seg = ast.get_source_segment(src, node) or ""
    seg = " ".join(seg.split())
    return seg if len(seg) <= limit else seg[:limit - 1] + "…"


class _ModuleAudit:
    """Trace + findings for one parsed module."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.sites: List[CollectiveSite] = []
        self.findings: List[CollectiveFinding] = []
        # callables that ARE collectives: name -> (kind, guarded)
        self.wrappers: Dict[str, Tuple[str, bool]] = {}
        self._run()

    # -- classification ------------------------------------------------
    def _collective_kind(self, call: ast.Call) -> Optional[Tuple[str, bool]]:
        """(kind, guarded) when `call` is a collective; None otherwise."""
        target = self.ctx.call_target(call)
        if target is None:
            return None
        leaf = target.split(".")[-1]
        if leaf == "guard":
            # resilience_retry.guard(name, fn, *args): kind from the fn
            # argument when resolvable, else from the label prefix
            kind = None
            if len(call.args) >= 2:
                fn = self.ctx.dotted(call.args[1])
                if fn is not None and fn.split(".")[-1] in COLLECTIVE_KINDS:
                    kind = COLLECTIVE_KINDS[fn.split(".")[-1]]
                elif fn is not None \
                        and fn.split(".")[-1] in self.wrappers:
                    kind = self.wrappers[fn.split(".")[-1]][0]
            if kind is None and call.args \
                    and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                kind = call.args[0].value.split(":")[0] or "collective"
            return (kind, True) if kind is not None else None
        if leaf in COLLECTIVE_KINDS:
            return COLLECTIVE_KINDS[leaf], self._inside_guard(call)
        if leaf in self.wrappers:
            kind, guarded = self.wrappers[leaf]
            return kind, guarded
        return None

    def _inside_guard(self, node: ast.AST) -> bool:
        """True when `node` sits inside a resilience_retry.guard(...) call
        (as an argument or in a lambda handed to it)."""
        cur = self.ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.Call):
                t = self.ctx.call_target(cur)
                if t is not None and t.split(".")[-1] == "guard":
                    return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = self.ctx.parent.get(cur)
        return False

    def _inside_telemetry(self, node: ast.AST) -> bool:
        """True when `node` executes under an explicit telemetry record:
        a ``with telemetry.scope(...)`` block, or an enclosing function
        decorated ``@telemetry.timed(...)``. (A histogram ``observe``
        call NEXT TO a site proves nothing about the site itself, so
        only enclosing-scope forms count.)"""
        cur = self.ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    if isinstance(item.context_expr, ast.Call):
                        t = self.ctx.call_target(item.context_expr)
                        if t is not None \
                                and t.split(".")[-1] == "scope":
                            return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in cur.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    t = self.ctx.dotted(d)
                    if t is not None and t.split(".")[-1] == "timed":
                        return True
            cur = self.ctx.parent.get(cur)
        return False

    # -- taint ---------------------------------------------------------
    # Call results are a TAINT BARRIER: the output of a collective (or
    # of any function that internally syncs) is rank-uniform by
    # construction, and cross-function data flow is out of scope — only
    # values a rank derives ARITHMETICALLY from its own rank id stay
    # tainted. A handful of value-transparent builtins pass taint
    # through (int(cuts[rank]) is still the rank's cut).
    _TRANSPARENT = {"int", "float", "bool", "abs", "min", "max"}

    def _expr_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Call):
            t = self.ctx.call_target(node)
            leaf = (t or "").split(".")[-1]
            if leaf in _RANK_CALLS:
                return True
            if leaf in self._TRANSPARENT:
                return any(self._expr_tainted(a, tainted)
                           for a in node.args)
            return False
        return any(self._expr_tainted(c, tainted)
                   for c in ast.iter_child_nodes(node))

    def _taint_function(self, fn: ast.AST) -> Set[str]:
        tainted: Set[str] = set(_RANK_NAMES)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if self.ctx.enclosing_function(node) is not fn:
                    continue          # nested defs have their own scope
                targets: List[str] = []
                value = None
                if isinstance(node, ast.Assign):
                    value = node.value
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            targets.append(t.id)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                        and isinstance(node.target, ast.Name) \
                        and node.value is not None:
                    value = node.value
                    targets.append(node.target.id)
                if value is None:
                    continue
                for name in targets:
                    if name not in tainted \
                            and self._expr_tainted(value, tainted):
                        tainted.add(name)
                        changed = True
        return tainted

    # -- trace walk ----------------------------------------------------
    def _func_of(self, node: ast.AST) -> str:
        parts: List[str] = []
        fn = self.ctx.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parts.append(fn.name)
            fn = self.ctx.enclosing_function(fn)
        return ".".join(reversed(parts))

    def _collect_sites(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            info = self._collective_kind(node)
            if info is None:
                continue
            kind, guarded = info
            target = (self.ctx.call_target(node) or "").split(".")[-1]
            name, payload = "", ""
            if target == "guard":
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                if len(node.args) >= 3:
                    payload = _snippet(self.ctx.source, node.args[2])
            elif node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) \
                        and isinstance(first.value, str):
                    name = first.value
                    if len(node.args) >= 2:
                        payload = _snippet(self.ctx.source, node.args[1])
                else:
                    payload = _snippet(self.ctx.source, first)
            # observation: the guard records op-kind latency+bytes
            # histograms itself (guard_records_telemetry proves it
            # statically), so every guarded site is observed by
            # construction; a direct call must sit under an explicit
            # telemetry span/timed decorator to count
            observed = ((guarded
                         and guard_records_telemetry(self.ctx.config))
                        or self._inside_telemetry(node))
            self.sites.append(CollectiveSite(
                kind=kind, path=self.ctx.relpath, line=node.lineno,
                func=self._func_of(node), name=name, payload=payload,
                guarded=guarded, observed=observed, node=node))

    def _discover_wrappers(self) -> None:
        """A module function whose body issues collectives is itself a
        collective from its callers' point of view (``_pallgather``,
        ``_allreduce_mean_host``): calling it under a rank-dependent
        branch diverges just the same. Fixpoint over direct bodies."""
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.ctx.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name in self.wrappers:
                    continue
                kinds: List[Tuple[str, bool]] = []
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and self.ctx.enclosing_function(sub) is node:
                        info = self._collective_kind(sub)
                        if info is not None:
                            kinds.append(info)
                if kinds:
                    # a wrapper named like an op (allreduce/broadcast)
                    # reports as that op; otherwise the first inner kind
                    kind = kinds[0][0]
                    for op in ("allreduce", "allgather", "broadcast",
                               "barrier"):
                        if op in node.name:
                            kind = op
                            break
                    self.wrappers[node.name] = (
                        kind, all(g for _, g in kinds))
                    changed = True

    # -- rank-consistency ----------------------------------------------
    def _sites_in(self, node: ast.AST) -> List[CollectiveSite]:
        body_nodes = set(ast.walk(node))
        return [s for s in self.sites if s.node in body_nodes]

    def _branch_seq(self, stmts: List[ast.stmt]) -> List[str]:
        nodes: Set[ast.AST] = set()
        for st in stmts:
            nodes.update(ast.walk(st))
        return [s.kind for s in sorted(
            (s for s in self.sites if s.node in nodes),
            key=lambda s: s.line)]

    def _check_consistency(self) -> None:
        for fn in ast.walk(self.ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            tainted = self._taint_function(fn)
            fn_sites = [s for s in self.sites
                        if s.node is not None
                        and s.node in set(ast.walk(fn))]
            if not fn_sites:
                continue
            for node in ast.walk(fn):
                if self.ctx.enclosing_function(node) is not fn:
                    continue          # nested defs analyze separately
                if isinstance(node, ast.If) \
                        and self._expr_tainted(node.test, tainted):
                    self._check_if(fn, node, tainted)
                elif isinstance(node, ast.While) \
                        and self._expr_tainted(node.test, tainted):
                    self._flag_all(node, node.test,
                                   "while loop on a rank-dependent "
                                   "condition")
                elif isinstance(node, ast.For) \
                        and self._expr_tainted(node.iter, tainted):
                    self._flag_all(node, node.iter,
                                   "for loop over a rank-dependent "
                                   "iteration space")

    def _cond_str(self, test: ast.AST) -> str:
        return _snippet(self.ctx.source, test, 48)

    def _flag_all(self, scope: ast.AST, test: ast.AST, why: str) -> None:
        cond = self._cond_str(test)
        for s in self._sites_in(scope):
            s.conditions = s.conditions + (cond,)
            self.findings.append(CollectiveFinding(
                path=s.path, line=s.line, func=s.func,
                message="%s '%s' reachable inside a %s (`%s`): ranks "
                        "disagreeing on it deadlock the collective"
                        % (s.kind, s.name or s.payload or "collective",
                           why, cond)))

    def _check_if(self, fn: ast.AST, node: ast.If,
                  tainted: Set[str]) -> None:
        cond = self._cond_str(node.test)
        seq_body = self._branch_seq(node.body)
        seq_else = self._branch_seq(node.orelse)
        if seq_body or seq_else:
            if seq_body == seq_else:
                return                    # symmetric: ranks still agree
            for st_list in (node.body, node.orelse):
                nodes: Set[ast.AST] = set()
                for st in st_list:
                    nodes.update(ast.walk(st))
                for s in self.sites:
                    if s.node in nodes:
                        s.conditions = s.conditions + (cond,)
                        self.findings.append(CollectiveFinding(
                            path=s.path, line=s.line, func=s.func,
                            message="%s '%s' is reachable only under "
                                    "rank-dependent condition `%s`: "
                                    "ranks taking the other branch "
                                    "never join it (deadlock)"
                                    % (s.kind,
                                       s.name or s.payload or "collective",
                                       cond)))
            return
        # no collectives inside, but an early exit in a rank-dependent
        # branch desequences every collective still ahead
        exits = [sub for arm in (node.body, node.orelse) for st in arm
                 for sub in ast.walk(st) if isinstance(sub, _EXITS)
                 and self.ctx.enclosing_function(sub)
                 is self.ctx.enclosing_function(node)]
        if not exits:
            return
        end = node.end_lineno or node.lineno
        later = [s for s in self.sites
                 if s.node is not None and s.line > end
                 and self.ctx.enclosing_function(s.node) is fn]
        for s in later:
            self.findings.append(CollectiveFinding(
                path=s.path, line=s.line, func=s.func,
                message="early exit under rank-dependent condition `%s` "
                        "(line %d) lets some ranks skip the %s '%s' "
                        "issued later in %s (deadlock)"
                        % (cond, node.lineno, s.kind,
                           s.name or s.payload or "collective",
                           s.func or "module scope")))

    def _run(self) -> None:
        self._discover_wrappers()
        self._collect_sites()
        self._check_consistency()


# ---------------------------------------------------------------------------
# guard instrumentation proof (collective_observed's base fact)
# ---------------------------------------------------------------------------

_GUARD_OBS_CACHE: Dict[str, bool] = {}


def guard_records_telemetry(config: Optional[GraftlintConfig] = None
                            ) -> bool:
    """Statically verify that ``resilience_retry.guard`` itself records
    telemetry (a histogram ``observe`` or span ``scope``) around the
    collectives it runs — the fact that makes every guarded site an
    OBSERVED site. Parsed once per root and cached; if guard ever loses
    its instrumentation, every guarded collective in the repo flips to
    unobserved and the ``collective_observed`` audit fails loudly."""
    root = (config.root if config is not None else ".") or "."
    cached = _GUARD_OBS_CACHE.get(root)
    if cached is not None:
        return cached
    candidates = [
        os.path.join(root, "lightgbm_tpu", "resilience", "retry.py"),
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "resilience", "retry.py"),
    ]
    ok = False
    for path in candidates:
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "guard":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        fn = sub.func
                        leaf = (fn.attr if isinstance(fn, ast.Attribute)
                                else getattr(fn, "id", ""))
                        if leaf in ("observe", "scope"):
                            ok = True
        break
    _GUARD_OBS_CACHE[root] = ok
    return ok


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_source(source: str, relpath: str,
                   config: Optional[GraftlintConfig] = None) -> _ModuleAudit:
    """Audit one in-memory module (the fixture-test entry point)."""
    config = config or GraftlintConfig()
    return _ModuleAudit(ModuleContext(source, relpath, config))


def check_fixture(source: str) -> List[str]:
    """Uniform fixture hook: divergence findings for a source snippet."""
    audit = analyze_source(source, "lightgbm_tpu/parallel/fixture.py")
    return [f.message for f in audit.findings]


def _audited_files(config: GraftlintConfig) -> List[str]:
    out = []
    for frag in config.collective_paths:
        ap = os.path.join(config.root, frag)
        if os.path.isfile(ap):
            out.append(frag)
            continue
        if not os.path.isdir(ap):
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          config.root).replace(os.sep, "/")
                    out.append(rel)
    return out


def _mesh_files(config: GraftlintConfig) -> List[str]:
    out = []
    for frag in getattr(config, "mesh_collective_paths", []):
        ap = os.path.join(config.root, frag)
        if os.path.isfile(ap):
            out.append(frag)
    return out


def audit_mesh_sites(config: Optional[GraftlintConfig] = None
                     ) -> List[CollectiveSite]:
    """In-program mesh-collective sites: every labeled
    ``plane_psum``/``vote_allgather`` call in the configured grower
    modules (``mesh-collective-paths``). These run INSIDE jitted SPMD
    programs — XLA sequences them identically on every shard, so the
    rank-consistency/guard audits do not apply — but they ARE the wire
    the quantized-histogram exchange ships on, so they ride the
    collective trace as ``mesh_sites`` for before/after diffing (and
    the trace-pin tests). A wrapper call without a literal label lands
    with ``name=""`` — the pin test treats that as a regression."""
    config = config or load_config()
    sites: List[CollectiveSite] = []
    for rel in _mesh_files(config):
        with open(os.path.join(config.root, rel), "r",
                  encoding="utf-8") as f:
            src = f.read()
        ctx = ModuleContext(src, rel, config)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.call_target(node)
            leaf = (target or "").split(".")[-1]
            if leaf not in MESH_WRAPPERS:
                continue
            name = ""
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                name = node.args[0].value
            payload = (_snippet(src, node.args[1])
                       if len(node.args) >= 2 else "")
            func = ""
            fn = ctx.enclosing_function(node)
            parts = []
            while fn is not None:
                if isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    parts.append(fn.name)
                fn = ctx.enclosing_function(fn)
            func = ".".join(reversed(parts))
            # guarded/observed stay honestly False: the retry guard and
            # the telemetry-span audit are HOST-side facts that do not
            # apply in-program (XLA sequences these; the flush-time
            # wire-byte histograms observe them in aggregate). The mesh
            # flag is what distinguishes them — they never enter the
            # guard/observed audits.
            sites.append(CollectiveSite(
                kind=MESH_WRAPPERS[leaf], path=rel, line=node.lineno,
                func=func, name=name, payload=payload, mesh=True))
    return sites


def audit_repo(config: Optional[GraftlintConfig] = None
               ) -> Tuple[List[CollectiveSite], List[CollectiveFinding]]:
    config = config or load_config()
    sites: List[CollectiveSite] = []
    findings: List[CollectiveFinding] = []
    for rel in _audited_files(config):
        with open(os.path.join(config.root, rel), "r",
                  encoding="utf-8") as f:
            src = f.read()
        audit = _ModuleAudit(ModuleContext(src, rel, config))
        sites.extend(audit.sites)
        findings.extend(audit.findings)
    return sites, findings


def extract_repo_trace(config: Optional[GraftlintConfig] = None,
                       artifact=None) -> dict:
    """The abstract collective trace for the --json payload: host-side
    DCN sites + findings, plus the in-program ``mesh_sites`` (the
    quantized plane reductions and the PV-Tree vote allgather)."""
    sites, findings = artifact if artifact is not None \
        else audit_repo(config)
    return {"sites": [s.to_dict() for s in sites],
            "findings": [f.to_dict() for f in findings],
            "mesh_sites": [s.to_dict()
                           for s in audit_mesh_sites(config)]}


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    """The gate entry point: two AuditResults (order + guard coverage).

    ``artifact`` takes a precomputed :func:`audit_repo` result so the
    --json CLI path walks the repo once, not once per consumer."""
    sites, findings = artifact if artifact is not None \
        else audit_repo(config)
    telemetry.count(C_SITES, len(sites), category="analysis")
    unguarded = [s for s in sites if not s.guarded]
    unobserved = [s for s in sites if not s.observed]
    if findings:
        telemetry.count(C_DIVERGENT, len(findings), category="analysis")
    if unguarded:
        telemetry.count(C_UNGUARDED, len(unguarded), category="analysis")
    if unobserved:
        telemetry.count(C_UNOBSERVED, len(unobserved),
                        category="analysis")
    order = AuditResult(
        name="collective_order",
        ok=not findings,
        detail=("%d site(s), rank-consistent" % len(sites))
        if not findings else "; ".join(
            "%s:%d %s" % (f.path, f.line, f.message)
            for f in findings[:3]))
    guard = AuditResult(
        name="collective_guarded",
        ok=not unguarded,
        detail=("%d DCN site(s) all guarded" % len(sites))
        if not unguarded else "; ".join(
            "%s:%d unguarded %s" % (s.path, s.line, s.kind)
            for s in unguarded[:3]))
    # an UNOBSERVED collective is invisible to the latency/bytes
    # histograms the ROADMAP item-2 rewrite measures against — every
    # site must record telemetry (the instrumented guard, a span, or a
    # timed decorator)
    observed = AuditResult(
        name="collective_observed",
        ok=not unobserved,
        detail=("%d DCN site(s) all record telemetry" % len(sites))
        if not unobserved else "; ".join(
            "%s:%d %s records no telemetry (no guard histogram, "
            "span, or timed scope)" % (s.path, s.line, s.kind)
            for s in unobserved[:3]))
    return [order, guard, observed]
