"""Strict-numerics harness for kernel-parity tests.

``strict_numerics()`` scopes two jax config flips:

* ``jax.numpy_dtype_promotion('strict')`` — any implicit promotion
  between two *non-weak* dtypes raises instead of silently widening.
  Weak Python scalars stay allowed (``f32_array + 0.5`` is fine); what
  dies is exactly the JG003 hazard class at runtime: an f64 value that
  leaked into f32 kernel math, or an i64 iota meeting an i32 index.
* ``jax.debug_nans`` — any NaN materializing in a jitted result raises
  at the producing op instead of surfacing 50 ops later as a wrong
  split choice.

The kernel-parity tests (test_pallas_histogram.py, test_block_scan.py)
run their kernel invocations under this context, so a dtype regression
in the hot kernels fails the parity suite even when the numeric outputs
happen to still match.
"""
from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def strict_numerics(debug_nans: bool = True):
    """Context manager: strict dtype promotion + NaN trapping."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(jax.numpy_dtype_promotion("strict"))
        if debug_nans:
            stack.enter_context(jax.debug_nans(True))
        yield
