"""Registry of the whole-program auditors behind the analysis gate.

Eight source/program-level audit engines complement the jaxpr audits
(:mod:`jaxpr_audit` traces real programs; these reason about the
source/geometry/dataflow statically):

* ``collective_order`` — rank-consistent DCN collective sequences +
  guard coverage (:mod:`collective_audit`);
* ``resource_budget`` — static VMEM/HBM budgets for the Pallas kernel
  fleet over the bench shapes (:mod:`resource_audit`);
* ``compile_surface`` — the analytic distinct-compile bound across the
  jitted entry points (:mod:`compile_audit`);
* ``precision_flow`` — every float narrowing in the traced ops/predict
  programs blessed or range-proven on the :mod:`dataflow` engine
  (:mod:`precision_audit`);
* ``transfer`` — no implicit device<->host transfer or oversized
  replicated intermediate in the persist/level/scan/predict programs
  (:mod:`transfer_audit`);
* ``quant_certify`` — static split-gain / leaf-output error bounds for
  the declared int8/int16/f16 quantization specs, shipped as the
  ``--json`` ``quant_certificate`` artifact (:mod:`quant_audit`);
* ``health_covered`` — every module that builds a persist/level scan
  driver must flush its device-side ``numerics::*`` health stats
  (:mod:`health_audit` — the runtime numerics sentinel's coverage
  gate);
* ``concurrency`` — lock discipline, blocking-hold, and acquisition
  order for the threaded host layer (serving loop, registry hot-swap,
  retry watchdog, telemetry registries), shipped as the ``--json``
  ``concurrency_trace`` artifact (:mod:`concurrency_audit`).

Each module exposes ``run(config) -> List[AuditResult]`` (the gate) and
``check_fixture(payload) -> List[str]`` (the seeded-violation hook the
fixture tests drive, parametrized over this registry exactly like the
JG lint rules — an auditor without fixtures fails CI by construction).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from . import (collective_audit, compile_audit, concurrency_audit,
               health_audit, precision_audit, quant_audit,
               resource_audit, transfer_audit)
from .config import GraftlintConfig
from .jaxpr_audit import AuditResult

AUDITORS: Dict[str, object] = {
    "collective_order": collective_audit,
    "resource_budget": resource_audit,
    "compile_surface": compile_audit,
    "precision_flow": precision_audit,
    "transfer": transfer_audit,
    "quant_certify": quant_audit,
    "health_covered": health_audit,
    "concurrency": concurrency_audit,
}


def all_auditors() -> Dict[str, object]:
    return dict(AUDITORS)


def compute_artifacts(config: Optional[GraftlintConfig] = None
                      ) -> Dict[str, object]:
    """One pass over the repo per auditor, keyed by registry name.

    The --json CLI needs both the pass/fail verdicts AND the full
    artifacts (trace, tables, surface, certificates); computing these
    here and passing them to :func:`run_all` + the payload builders
    keeps that to a single walk instead of one per consumer."""
    profile = resource_audit._resolve_profile(config)
    kernels, hbm = resource_audit.estimate_all(profile)
    return {
        "collective_order": collective_audit.audit_repo(config),
        "resource_budget": (profile, kernels, hbm),
        "compile_surface": compile_audit.iter_jit_sites(config),
        "precision_flow": precision_audit.compute_artifact(config),
        "transfer": transfer_audit.compute_artifact(config),
        "quant_certify": quant_audit.compute_artifact(config),
        "health_covered": health_audit.compute_artifact(config),
        "concurrency": concurrency_audit.compute_artifact(config),
    }


def run_all(config: Optional[GraftlintConfig] = None,
            artifacts: Optional[Dict[str, object]] = None
            ) -> List[AuditResult]:
    artifacts = artifacts or {}
    out: List[AuditResult] = []
    for name in sorted(AUDITORS):
        out.extend(AUDITORS[name].run(config,
                                      artifact=artifacts.get(name)))
    return out
