"""Static VMEM/HBM budget estimator for the Pallas kernel fleet.

Level-wise GPU learners pin their memory plan before training starts
("XGBoost: Scalable GPU Accelerated Learning" builds its entire
device-memory layout up front); the TPU kernels here instead size
per-kernel ``vmem_limit_bytes`` requests at build time — numbers that
were only ever validated by running on a real TPU. This module makes
the plan static: for every ``pallas_call`` family in
``ops/pallas_histogram.py`` / ``ops/pallas_scan.py`` /
``ops/pallas_grow.py`` it derives, per bench shape
(higgs/expo/allstate/yahoo/msltr — the ``data/synth.py`` generators'
geometries), two numbers:

* the **request** — the scoped-vmem limit the kernel itself asks for,
  computed by the SAME helper the kernel calls
  (``hist_vmem_plan`` / ``scan_pair_vmem_bytes`` /
  ``split_pass_vmem_bytes`` …), so the audit can never drift from the
  code;
* an independent **estimate** — the double-buffered BlockSpec blocks
  plus scratch shapes plus the kernel's arithmetic temporaries, derived
  here from the grid/block geometry.

The gate fails when an estimate exceeds its request (the kernel would
OOM inside its own limit) or a request exceeds the per-core VMEM budget
of the active device profile (``telemetry/devices.py``). An HBM tally
(payload + binned planes + scores/gradients + per-leaf histogram
planes) is checked against the per-chip HBM budget the same way.

``tables()`` renders both as rows for the CLI (text + ``--json``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import events as telemetry
from ..telemetry.devices import DeviceProfile, detect_profile, get_profile
from .config import GraftlintConfig, load_config
from .jaxpr_audit import AuditResult

C_KERNELS = "analysis::resource_kernels"
C_OVER = "analysis::resource_over_budget"

MIB = 1 << 20

# persist level-program batching (pallas_grow make_level_pass defaults)
_S_MAXL = 16
_NUM_LEAVES = 255          # the bench configs' tree size (255-leaf trees)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class BenchShape:
    """Static geometry of one bench dataset (data/synth.py defaults).

    ``groups`` is the post-EFB feature-group count: unbundled datasets
    carry one byte group per feature; bundled ones pack their one-hot
    blocks into <=255-offset byte groups (Expo's 648 features bundle to
    18 groups; Allstate's ~4218 one-hot columns to ~17 plus the 8
    numerics)."""

    name: str
    rows: int
    features: int
    groups: int
    bundled: bool
    max_bin: int = 255

    @property
    def W(self) -> int:
        return 256


BENCH_SHAPES: Dict[str, BenchShape] = {
    "higgs": BenchShape("higgs", rows=10_500_000, features=28, groups=28,
                        bundled=False),
    "expo": BenchShape("expo", rows=2_000_000, features=648, groups=18,
                       bundled=True),
    "allstate": BenchShape("allstate", rows=1_000_000, features=4226,
                           groups=25, bundled=True),
    "yahoo": BenchShape("yahoo", rows=473_134, features=700, groups=700,
                        bundled=False),
    "msltr": BenchShape("msltr", rows=2_270_000, features=137, groups=137,
                        bundled=False),
}


@dataclass
class KernelEstimate:
    """One (kernel, shape) VMEM check."""

    kernel: str
    shape: str
    geometry: str
    request: int               # vmem_limit_bytes the kernel asks for
    estimate: int              # BlockSpec+scratch footprint derived here
    budget: int                # per-core VMEM budget of the profile
    ok: bool = True
    why: str = ""

    def to_dict(self) -> dict:
        return {"kernel": self.kernel, "shape": self.shape,
                "geometry": self.geometry, "request": self.request,
                "estimate": self.estimate, "budget": self.budget,
                "ok": self.ok, "why": self.why}


@dataclass
class HBMEstimate:
    """One shape's resident-plane tally."""

    shape: str
    components: Dict[str, int]
    budget: int

    @property
    def total(self) -> int:
        return sum(self.components.values())

    @property
    def ok(self) -> bool:
        return self.total <= self.budget

    def to_dict(self) -> dict:
        return {"shape": self.shape, "components": dict(self.components),
                "total": self.total, "budget": self.budget, "ok": self.ok}


def _check(est: KernelEstimate) -> KernelEstimate:
    if est.request > est.budget:
        est.ok = False
        est.why = ("requests %.1fMB scoped vmem > %.1fMB per-core budget"
                   % (est.request / MIB, est.budget / MIB))
    elif est.estimate > est.request:
        est.ok = False
        est.why = ("blocks+scratch need %.1fMB > the %.1fMB limit the "
                   "kernel requests" % (est.estimate / MIB,
                                        est.request / MIB))
    return est


def _payload_geom(shape: BenchShape):
    """(WPA, C, NP, nbw) via the REAL grow_persist plan/geometry."""
    from ..ops.grow_persist import _payload_geometry, _payload_plan
    widths = np.full(shape.groups, shape.max_bin + 1, np.int64)
    _plan, nbw = _payload_plan(widths)
    WPA, C, NP = _payload_geometry(shape.rows, nbw, 0, 16384)
    return WPA, C, NP, nbw


# ---------------------------------------------------------------------------
# per-kernel estimators (geometry -> KernelEstimate)
# ---------------------------------------------------------------------------

def estimate_hist_window(shape: BenchShape,
                         profile: DeviceProfile) -> KernelEstimate:
    from ..ops.pallas_histogram import hist_vmem_plan
    G = shape.groups
    # the serial learner's auto chunk: bound the scatter tensor to ~256MB
    C = max(1 << 14, int(2 ** 25 / max(G, 1)))
    plan = hist_vmem_plan(shape.W, G, C)
    ct, w_pad = plan["ct"], plan["w_pad"]
    out_bytes = (G * 16 * 16 * 2 * 4 if plan["use_radix"]
                 else G * w_pad * 2 * 4)
    temps = (3 * 16 * ct * 2 + 4 * 16 * 16 * 4 if plan["use_radix"]
             else w_pad * ct * 2 + w_pad * 4 * 4)
    est = 2 * (G * ct * 4 + ct * 4 * 2 + out_bytes) + temps
    return _check(KernelEstimate(
        kernel="hist_window", shape=shape.name,
        geometry="G=%d ct=%d %s" % (G, ct,
                                    "radix" if plan["use_radix"]
                                    else "onehot"),
        request=plan["vmem_limit"], estimate=int(est),
        budget=profile.vmem_budget))


def estimate_scan_pair(shape: BenchShape,
                       profile: DeviceProfile) -> KernelEstimate:
    from ..ops.pallas_scan import scan_pair_vmem_bytes
    Fp = _round_up(max(shape.features, 8), 8)
    Wp = _round_up(shape.W, 128)
    blocks = 2 * (6 * Fp * Wp * 4 + 128 * 4 + 2 * 8 * Fp * 4)
    temps = 12 * Fp * Wp * 4 + Wp * Wp * 4 + 8 * Fp * Wp * 4
    return _check(KernelEstimate(
        kernel="scan_pair", shape=shape.name,
        geometry="Fp=%d Wp=%d" % (Fp, Wp),
        request=scan_pair_vmem_bytes(Fp, Wp),
        estimate=int(blocks + temps), budget=profile.vmem_budget))


def estimate_scan_blocks(shape: BenchShape,
                         profile: DeviceProfile) -> KernelEstimate:
    from ..ops.pallas_scan import scan_blocks_vmem_bytes
    Gp = _round_up(max(shape.groups, 8), 8)
    Wp = _round_up(shape.W, 128)
    blocks = 2 * (2 * Gp * Wp * 4 + 8 * Gp * Wp * 4 + 128 * 4
                  + 8 * Gp * 4)
    temps = 12 * Gp * Wp * 4 + Wp * Wp * 4 + 10 * Gp * Wp * 4
    return _check(KernelEstimate(
        kernel="scan_blocks", shape=shape.name,
        geometry="Gp=%d Wp=%d" % (Gp, Wp),
        request=scan_blocks_vmem_bytes(Gp, Wp),
        estimate=int(blocks + temps), budget=profile.vmem_budget))


def estimate_split_pass(shape: BenchShape, profile: DeviceProfile,
                        level: bool = False) -> KernelEstimate:
    from ..ops.pallas_grow import split_pass_vmem_bytes
    WPA, C, _NP, nbw = _payload_geom(shape)
    E = C + 128
    G = shape.groups
    # scratch_shapes: wbuf/obuf/rbuf + 4 FIFO slots (WP_LIVE <= WPA rows)
    scratch = (3 * WPA * E + 4 * WPA * E) * 4 + G * 16 * 64 * 4
    # decode temporaries: group-bin planes + the radix one-hot contraction
    temps = G * E * 4 + 64 * E * 2 + 2 * 16 * E * 2
    return _check(KernelEstimate(
        kernel="level_pass" if level else "split_pass", shape=shape.name,
        geometry="WPA=%d E=%d G=%d nbw=%d" % (WPA, E, G, nbw),
        request=split_pass_vmem_bytes(WPA, E, G),
        estimate=int(scratch + temps), budget=profile.vmem_budget))


def estimate_seg_hist(shape: BenchShape, profile: DeviceProfile,
                      root: bool = False) -> KernelEstimate:
    from ..ops.pallas_grow import seg_hist_vmem_bytes
    WPA, C, _NP, nbw = _payload_geom(shape)
    E = 16384 if root else C + 128      # root_hist streams CR=16384 chunks
    G = shape.groups
    scratch = (2 if not root else 1) * WPA * E * 4 + G * 16 * 64 * 4
    temps = G * E * 4 + 64 * E * 2 + 2 * 16 * E * 2
    return _check(KernelEstimate(
        kernel="root_hist" if root else "seg_hist", shape=shape.name,
        geometry="WPA=%d E=%d G=%d" % (WPA, E, G),
        request=seg_hist_vmem_bytes(WPA, E, G),
        estimate=int(scratch + temps), budget=profile.vmem_budget))


def estimate_hbm(shape: BenchShape, profile: DeviceProfile) -> HBMEstimate:
    WPA, _C, NP, _nbw = _payload_geom(shape)
    comps = {
        # the persist payload: every training plane in one [WPA, NP] u32
        "payload": WPA * NP * 4,
        # the binned Dataset (byte groups; the payload is packed FROM it,
        # both resident during build)
        "binned": shape.rows * shape.groups,
        # f64 score buffer + f32 grad/hess (v1/fallback paths)
        "scores": shape.rows * 8,
        "grad_hess": 2 * shape.rows * 4,
        # per-leaf parent histograms retained for parent-minus-smaller
        "hist_planes": _NUM_LEAVES * shape.groups * shape.W * 2 * 4,
        # the level program's batched smaller-child histograms
        "level_hists": _S_MAXL * shape.groups * 16 * 64 * 4,
    }
    return HBMEstimate(shape=shape.name, components=comps,
                       budget=profile.hbm_budget)


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def _resolve_profile(config: Optional[GraftlintConfig]) -> DeviceProfile:
    config = config or load_config()
    name = getattr(config, "audit_device", "v5e")
    if name != "auto":
        return get_profile(name)
    profile = detect_profile()
    if profile.name == "cpu":
        # detect_profile's "cpu" entry exists for honest bench-round
        # meta/roofline on accelerator-less boxes; budgeting the Pallas
        # kernel fleet against a 16MB host envelope is meaningless —
        # "auto" on CPU keeps auditing against the TPU tuning target,
        # the pre-"cpu"-profile contract
        return get_profile("v5e")
    return profile


def estimate_all(profile: Optional[DeviceProfile] = None,
                 config: Optional[GraftlintConfig] = None):
    """(kernel estimates, hbm estimates) over every bench shape, routing
    each shape through the kernels it actually runs (bundled shapes take
    the block scan; unbundled the per-feature pair scan)."""
    profile = profile or _resolve_profile(config)
    kernels: List[KernelEstimate] = []
    hbm: List[HBMEstimate] = []
    for shape in BENCH_SHAPES.values():
        kernels.append(estimate_hist_window(shape, profile))
        if shape.bundled:
            kernels.append(estimate_scan_blocks(shape, profile))
        else:
            kernels.append(estimate_scan_pair(shape, profile))
        kernels.append(estimate_split_pass(shape, profile))
        kernels.append(estimate_split_pass(shape, profile, level=True))
        kernels.append(estimate_seg_hist(shape, profile))
        kernels.append(estimate_seg_hist(shape, profile, root=True))
        hbm.append(estimate_hbm(shape, profile))
    return kernels, hbm


def check_fixture(geom: dict) -> List[str]:
    """Uniform fixture hook: budget violations for a synthetic geometry
    dict (name/rows/features/groups/bundled [+ profile])."""
    profile = get_profile(geom.get("profile", "v5e"))
    shape = BenchShape(name=geom.get("name", "fixture"),
                       rows=int(geom["rows"]),
                       features=int(geom["features"]),
                       groups=int(geom["groups"]),
                       bundled=bool(geom.get("bundled", False)))
    ests = [estimate_hist_window(shape, profile),
            (estimate_scan_blocks if shape.bundled
             else estimate_scan_pair)(shape, profile),
            estimate_split_pass(shape, profile)]
    out = [("%s@%s: %s" % (e.kernel, e.geometry, e.why))
           for e in ests if not e.ok]
    h = estimate_hbm(shape, profile)
    if not h.ok:
        out.append("hbm: %.2fGB resident > %.2fGB budget"
                   % (h.total / 2 ** 30, h.budget / 2 ** 30))
    return out


def tables(profile: Optional[DeviceProfile] = None,
           config: Optional[GraftlintConfig] = None,
           artifact=None) -> dict:
    """The budget tables for the CLI (text renderer + --json payload)."""
    if artifact is not None:
        profile, kernels, hbm = artifact
    else:
        profile = profile or _resolve_profile(config)
        kernels, hbm = estimate_all(profile)
    return {"profile": profile.to_dict(),
            "vmem": [k.to_dict() for k in kernels],
            "hbm": [h.to_dict() for h in hbm]}


def render_tables(t: dict) -> str:
    lines = ["resource budgets (profile %s: vmem %dMB/core, hbm %.0fGB"
             "/chip)" % (t["profile"]["name"],
                         t["profile"]["vmem_budget"] // MIB,
                         t["profile"]["hbm_budget"] / 2 ** 30)]
    lines.append("  %-12s %-9s %-28s %9s %9s %s"
                 % ("kernel", "shape", "geometry", "req(MB)", "est(MB)",
                    "ok"))
    for k in t["vmem"]:
        lines.append("  %-12s %-9s %-28s %9.1f %9.1f %s"
                     % (k["kernel"], k["shape"], k["geometry"],
                        k["request"] / MIB, k["estimate"] / MIB,
                        "ok" if k["ok"] else "OVER: " + k["why"]))
    lines.append("  %-12s %-9s %14s %14s %s"
                 % ("hbm", "shape", "resident(GB)", "budget(GB)", "ok"))
    for h in t["hbm"]:
        lines.append("  %-12s %-9s %14.2f %14.2f %s"
                     % ("hbm", h["shape"], h["total"] / 2 ** 30,
                        h["budget"] / 2 ** 30,
                        "ok" if h["ok"] else "OVER"))
    return "\n".join(lines)


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    """The gate entry point: one AuditResult for VMEM, one for HBM.

    ``artifact`` takes a precomputed ``(profile, kernels, hbm)`` so the
    --json CLI path estimates the kernel fleet once, not twice."""
    if artifact is not None:
        profile, kernels, hbm = artifact
    else:
        profile = _resolve_profile(config)
        kernels, hbm = estimate_all(profile)
    telemetry.count(C_KERNELS, len(kernels), category="analysis")
    bad_k = [k for k in kernels if not k.ok]
    bad_h = [h for h in hbm if not h.ok]
    if bad_k or bad_h:
        telemetry.count(C_OVER, len(bad_k) + len(bad_h),
                        category="analysis")
    vmem = AuditResult(
        name="vmem_budget",
        ok=not bad_k,
        detail=("%d kernel/shape combos within %dMB (%s)"
                % (len(kernels), profile.vmem_budget // MIB, profile.name))
        if not bad_k else "; ".join(
            "%s@%s %s" % (k.kernel, k.shape, k.why) for k in bad_k[:3]))
    hbm_res = AuditResult(
        name="hbm_budget",
        ok=not bad_h,
        detail=("%d shapes resident within %.0fGB (%s)"
                % (len(hbm), profile.hbm_budget / 2 ** 30, profile.name))
        if not bad_h else "; ".join(
            "%s: %.2fGB > %.2fGB" % (h.shape, h.total / 2 ** 30,
                                     h.budget / 2 ** 30)
            for h in bad_h[:3]))
    return [vmem, hbm_res]
