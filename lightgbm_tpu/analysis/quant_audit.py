"""Quantization certifier: static error bounds for the quantized paths.

ROADMAP item 2 ships int8/int16 histogram payloads with stochastic
rounding over DCN (the PV-Tree regime); item 3 ships f16 leaf/threshold
serving tensors.  Both narrow the numerics exactly where the tie-flip
lived — so this auditor certifies the quantization contracts BEFORE
those PRs land, and emits a machine-checkable ``quant_certificate``
block in ``--json`` that they must ship green against.

**Histogram planes** (``kind: "histogram"``).  Input contract (seeded
from ``ops/pallas_histogram.hist_input_contract`` /
``ops/grow_persist.persist_input_contract``): per-row |grad| <= g_max,
0 <= hess <= h_max, so every per-rank bin sum AND every prefix/subset
sum is capped by ``S = rows_per_rank * cap``.  Each rank quantizes its
[G, W] planes symmetrically at that contract scale (step
``delta = 2 S / (2^bits - 2)``) with *stochastic rounding*: per-entry
error is zero-mean and bounded by ``delta``.  A split decision reads
prefix sums over at most ``W`` bins of ``R`` rank contributions —
``N = W * R`` independent bounded zero-mean errors — so by Hoeffding
the accumulated error stays within ``E = delta * sqrt(2 N ln(2/CONF))``
except with probability :data:`CONFIDENCE` per decision (the
deterministic worst case ``N * delta`` is also reported).  The
certified decision domain is the PV-Tree candidate regime: splits
whose children each hold at least :data:`H_CHILD_FRAC` of the total
hessian mass (top-k voted features are exactly the high-mass ones).
Over that domain the split-gain perturbation is bounded through the
gain's partial derivatives (``gain = G^2/(H + lambda)``, three terms:
left/right/parent)::

    d_eff  = lambda + H_CHILD_FRAC * S_h_global - E_H   (must be > 0)
    dgain <= 3 * (2 * S_g_global / d_eff * E_G
                  + (S_g_global / d_eff)^2 * E_H)

and the certificate's headline number is ``dgain`` relative to the
certified-domain gain cap ``S_g_global^2 / (lambda + frac * S_h)``,
gated against the pinned :data:`SPLIT_DECISION_BUDGET`.  int16 at the
higgs/expo geometries certifies with margin; int8 at full plane scale
blows the budget by >100x — the registry fixture pins both, and
``tests/test_dataflow.py`` checks the bound against an empirical max
over 1k random payloads.

**Leaf/threshold tensors** (``kind: "leaf"``, spec from
``predict/compile.quant_spec``).  f16 keeps 11 mantissa bits: each
stored leaf is within relative ``2^-11`` of its f64 value, so the
ensemble output error is ``num_trees * leaf_abs_max * 2^-11`` absolute
— relative ``2^-11`` of the output scale — and an f16 threshold moves
each decision boundary by at most relative ``2^-11``; both gate
against :data:`PREDICT_REL_BUDGET`.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..telemetry import events as telemetry
from .config import GraftlintConfig
from .jaxpr_audit import AuditResult

C_CERTIFIED = "analysis::quant_certified"

# pinned budgets: the split-decision budget is the relative split-gain
# perturbation a certified quantization may induce over the certified
# decision domain; the predict budget is the relative output/boundary
# error the serving tensors may carry
SPLIT_DECISION_BUDGET = 0.05
PREDICT_REL_BUDGET = 1e-3

# certified decision domain: each child of a certified split holds at
# least this fraction of the total hessian mass (the PV-Tree top-k
# candidate regime — low-mass splits are exactly the ones voting prunes)
H_CHILD_FRAC = 0.25
# per-decision failure probability of the Hoeffding accumulation bound
CONFIDENCE = 1e-9

_BITS = {"int8": 8, "int16": 16}
_F16_REL = 2.0 ** -11


def default_specs(config: Optional[GraftlintConfig] = None
                  ) -> List[dict]:
    """The specs the gate certifies every run: int16 histogram planes
    at the higgs and expo bench geometries (contract caps from
    ops/pallas_histogram.hist_input_contract), and the f16 serving
    tensors (predict/compile.quant_spec defaults)."""
    from ..ops.pallas_histogram import hist_input_contract
    from ..predict.compile import quant_spec
    from .resource_audit import BENCH_SHAPES
    specs = []
    for name in ("higgs", "expo"):
        shape = BENCH_SHAPES[name]
        ranks = 8
        rows_shard = shape.rows // ranks
        contract = hist_input_contract(w=256, rows=rows_shard)
        specs.append({
            "name": "hist_int16_%s" % name,
            "kind": "histogram",
            "target": "int16",
            "stochastic": True,
            "rows_per_rank": rows_shard,
            "ranks": ranks,
            "bins": 256,
            "g_max": contract["grad"][1],
            "h_max": contract["hess"][1],
            "lambda": 1.0,
        })
    specs.append(quant_spec())
    return specs


def certify(spec: dict) -> dict:
    """One certificate: the spec, every intermediate constant, the
    bound, the budget, and the verdict — machine-checkable, and the
    empirical test recomputes the same numbers."""
    if spec.get("kind") == "histogram":
        return _certify_histogram(spec)
    return _certify_leaf(spec)


def _certify_histogram(spec: dict) -> dict:
    bits = _BITS[spec["target"]]
    rows = int(spec["rows_per_rank"])
    ranks = int(spec["ranks"])
    W = int(spec.get("bins", 256))
    g_max = float(spec.get("g_max", 1.0))
    h_max = float(spec.get("h_max", 0.25))
    lam = float(spec.get("lambda", 1.0))
    stochastic = bool(spec.get("stochastic", True))

    s_g = rows * g_max                 # per-rank plane scale (contract)
    s_h = rows * h_max
    levels = (1 << bits) - 2           # symmetric, one code reserved
    delta_g = 2.0 * s_g / levels
    delta_h = 2.0 * s_h / levels
    n_terms = W * ranks
    hoeffding = math.sqrt(2.0 * n_terms * math.log(2.0 / CONFIDENCE))
    if stochastic:
        e_g = delta_g * hoeffding
        e_h = delta_h * hoeffding
    else:                              # nearest rounding: worst case
        e_g = n_terms * delta_g / 2.0
        e_h = n_terms * delta_h / 2.0
    s_g_global = ranks * s_g
    s_h_global = ranks * s_h
    d = lam + H_CHILD_FRAC * s_h_global
    d_eff = d - e_h
    cert = {
        "spec": dict(spec),
        "scale_grad": s_g, "scale_hess": s_h,
        "step_grad": delta_g, "step_hess": delta_h,
        "accum_terms": n_terms,
        "confidence": CONFIDENCE,
        "err_grad": e_g, "err_hess": e_h,
        "err_grad_worst": n_terms * delta_g,
        "err_hess_worst": n_terms * delta_h,
        "h_child_frac": H_CHILD_FRAC,
        "budget": SPLIT_DECISION_BUDGET,
    }
    if d_eff <= 0.0:
        cert.update(gain_perturbation=float("inf"),
                    bound=float("inf"), ok=False,
                    why="hessian quantization error %.3g swamps the "
                        "certified child mass %.3g" % (e_h, d))
        return cert
    dgain = 3.0 * (2.0 * s_g_global / d_eff * e_g
                   + (s_g_global / d_eff) ** 2 * e_h)
    gain_cap = s_g_global ** 2 / d
    rel = dgain / gain_cap
    cert.update(gain_perturbation=dgain, gain_cap=gain_cap,
                bound=rel, ok=rel <= SPLIT_DECISION_BUDGET,
                margin=(SPLIT_DECISION_BUDGET / rel if rel > 0.0
                        else float("inf")))
    return cert


def _certify_leaf(spec: dict) -> dict:
    target = spec.get("target")
    if target in ("float16", "f16"):
        rel = _F16_REL
    elif target == "int8":
        # symmetric int8 value grid: step = 2*cap/254, worst relative
        # error 1/127 (~2^-7) of the tensor scale — 8x the predict
        # budget, so the serving registry's quantized-load seam refuses
        # this certificate by name (leaf_int8)
        rel = 1.0 / (((1 << _BITS["int8"]) - 2) // 2)
    else:
        rel = 2.0 ** -8     # bf16 serving would keep 8 bits
    trees = int(spec.get("num_trees", 1))
    leaf_cap = float(spec.get("leaf_abs_max", 1.0))
    out_abs = trees * leaf_cap * rel
    cert = {
        "spec": dict(spec),
        "leaf_rel_err": rel,
        "output_abs_err": out_abs,
        "output_scale": trees * leaf_cap,
        "threshold_rel_shift": rel,
        "budget": PREDICT_REL_BUDGET,
        "bound": rel,
        "ok": rel <= PREDICT_REL_BUDGET,
        "margin": PREDICT_REL_BUDGET / rel,
    }
    return cert


def compute_artifact(config: Optional[GraftlintConfig] = None
                     ) -> List[dict]:
    return [certify(s) for s in default_specs(config)]


def certificate_payload(config: Optional[GraftlintConfig] = None,
                        artifact=None) -> Dict[str, object]:
    """The ``--json`` ``quant_certificate`` block: one entry per spec
    plus the pinned budgets — the artifact the item-2/item-3 PRs must
    ship green against."""
    certs = artifact if isinstance(artifact, list) \
        else compute_artifact(config)
    return {
        "budgets": {"split_decision": SPLIT_DECISION_BUDGET,
                    "predict_rel": PREDICT_REL_BUDGET},
        "h_child_frac": H_CHILD_FRAC,
        "confidence": CONFIDENCE,
        "certificates": certs,
        "all_ok": all(c["ok"] for c in certs),
    }


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    name = "quant_certify"
    try:
        certs = artifact if isinstance(artifact, list) \
            else compute_artifact(config)
    except Exception as e:      # pragma: no cover - defensive
        return [AuditResult(name=name, ok=False,
                            detail="auditor raised: %r" % e)]
    bad = [c for c in certs if not c["ok"]]
    telemetry.count(C_CERTIFIED, len(certs) - len(bad),
                    category="analysis")
    if bad:
        bits = ["%s: bound %.3g > budget %.3g"
                % (c["spec"].get("name", c["spec"].get("kind")),
                   c["bound"], c["budget"]) for c in bad[:3]]
        return [AuditResult(name=name, ok=False,
                            detail="; ".join(bits))]
    worst = max((c["bound"] / c["budget"] for c in certs),
                default=0.0)
    return [AuditResult(
        name=name, ok=True,
        detail="%d spec(s) certified; tightest margin %.1fx"
               % (len(certs), 1.0 / worst if worst else float("inf")))]


def check_fixture(payload: dict) -> List[str]:
    """Uniform fixture hook: a spec dict — int8 at full plane scale
    must blow the split-decision budget, int16 must certify."""
    cert = certify(payload)
    if cert["ok"]:
        return []
    return ["%s: bound %.3g exceeds budget %.3g (%s)"
            % (payload.get("name", payload.get("kind", "spec")),
               cert["bound"], cert["budget"], cert.get("why", ""))]
