"""Recompile-surface auditor: bound the distinct-compile count statically.

A jitted entry point recompiles once per distinct static-argument
tuple (plus once per input-shape bucket). The serving path already pins
its shape ladder analytically (``jaxpr_audit.audit_serve_ladder``);
this module generalizes that bound to the whole program: enumerate
every ``jax.jit`` site across ``ops/``, ``predict/`` and the level
driver (``treelearner/``) via AST, read off its static-argument
signature, and multiply each argument's value-domain size from the
registry below. The audit fails on

* an **unbounded static-arg**: a name with no registered domain — the
  classic storm is a Python int that varies per iteration (a leaf
  count, a chunk index) quietly marked static;
* a total analytic bound above the configured ceiling
  (``[tool.graftlint] compile-ceiling``) — the budget a training +
  serving run is allowed to spend on compiles.

The domain registry is deliberately explicit: adding a static arg to a
kernel REQUIRES adding its domain here (or the gate fails), which is
the point — every new compile axis is a reviewed decision, the way new
lint rules require fixtures. Factory-built jits (the ``make_*`` kernel
builders' inner ``@jax.jit``) count 1 each: JG004 already polices that
builders stay out of host loops, so each contributes one compile per
payload geometry.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .core import ModuleContext
from .jaxpr_audit import AuditResult

C_ENTRIES = "analysis::compile_entries"
C_BOUND = "analysis::compile_bound"
C_UNBOUNDED = "analysis::compile_unbounded"

# directories whose jit sites form the training/serving compile surface
AUDIT_ROOTS = ("lightgbm_tpu/ops", "lightgbm_tpu/predict",
               "lightgbm_tpu/treelearner", "lightgbm_tpu/serving",
               "lightgbm_tpu/multimodel")

# static-argument value domains: name -> (size, why). A size of 1 means
# "constant for a whole run" (dataset geometry, config); sizes > 1
# enumerate the values a single run can actually see.
DOMAINS: Dict[str, Tuple[int, str]] = {
    "interpret": (1, "False outside the parity tests"),
    "do_fix": (2, "bundled datasets run both fix modes"),
    "w": (2, "per-dataset max width; <=2 pad ladder stops (128/256)"),
    "max_w": (1, "per-dataset categorical width"),
    "use_dp": (1, "config constant"),
    "use_mc": (1, "per-dataset monotone flag"),
    "num_features": (1, "dataset geometry"),
    "gc": (1, "one GrowConfig per learner"),
    "axis_name": (1, "mesh constant"),
    "total_bins": (1, "dataset geometry"),
    "rows_per_chunk": (1, "resolved once per learner"),
    "dtype": (2, "hist dtype: run dtype + the f64 parity twin"),
    "num_class": (1, "config constant"),
    "use_l1": (1, "config constant (lambda_l1 > 0)"),
    "use_mds": (1, "config constant (max_delta_step > 0)"),
    "feat_gains_only": (2, "CEGB feature-gain pre-pass runs both modes"),
    "k": (3, "fused scan batch sizes clamp to {1..8,16} minus "
             "snapshot alignment; bounded by the batch ladder"),
    "quant": (1, "one certified HistQuant (or None) per learner — "
                 "resolved from tpu_hist_quant at config time"),
    # fused boosting iteration (PR 17): the scan-driver factory caches
    # one compiled program per (mode, objective-kernel id, k,
    # bag_spec) — `mode` and the kernel id are factory-closure axes
    # today, but registering them here makes the compile cost of any
    # future static-arg promotion a reviewed decision, and bounds the
    # per-learner driver-cache fan-out the same way
    "mode": (2, "driver program family: {gbdt, rf} (dart rides gbdt "
                "k=1 programs)"),
    "grad_kernel": (1, "one objective per learner -> one device "
                       "gradient kernel per driver cache"),
    "cls": (4, "DART delta gather-add compiles once per class id it "
               "touches; bounded by num_class (1 for the audited "
               "binary/regression surface, small for multiclass)"),
    # serving static args (serving/ rides predict's jitted entry points;
    # these bound any future serving-local jit site the same way)
    "quant_target": (2, "serving value grids: native + the certified "
                        "f16 twin (coarser grids are refused at load)"),
    "raw_score": (2, "serving transform flag: {True, False}"),
}

# site-specific domains for static_argnums on functions whose parameter
# names the AST walk cannot resolve (bound methods): keyed by
# (file basename, function-or-target name, argnum)
SITE_DOMAINS: Dict[Tuple[str, str, int], Tuple[int, str]] = {
    ("runtime.py", "self._forward_raw", 1): (2, "raw flag: {True, False}"),
}


@dataclass
class JitSite:
    """One jit construction site and its static-argument signature."""

    path: str
    line: int
    func: str                      # decorated/wrapped callable name
    kind: str                      # "decorator" | "call" | "factory"
    static_names: Tuple[str, ...] = ()
    static_nums: Tuple[int, ...] = ()
    bound: int = 1
    unbounded: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "func": self.func,
                "kind": self.kind,
                "static_names": list(self.static_names),
                "static_nums": list(self.static_nums),
                "bound": self.bound, "unbounded": list(self.unbounded)}


def _const_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return tuple(out)
    return ()


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (int(node.value),)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(int(el.value) for el in node.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, int))
    return ()


class _ModuleScan:
    """Jit sites of one parsed module."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.sites: List[JitSite] = []
        self._scan()

    def _jit_call_info(self, call: ast.Call) -> Optional[dict]:
        """Parse a jax.jit(...) / partial(jax.jit, ...) call node."""
        target = self.ctx.call_target(call)
        if target in ("jax.jit", "jax.pmap", "jit"):
            kw = {k.arg: k.value for k in call.keywords}
            fn = ""
            if call.args:
                fn = ast.get_source_segment(self.ctx.source,
                                            call.args[0]) or ""
            return {"fn": fn, "kw": kw}
        if target in ("functools.partial", "partial") and call.args \
                and self.ctx.dotted(call.args[0]) in ("jax.jit",
                                                      "jax.pmap", "jit"):
            kw = {k.arg: k.value for k in call.keywords}
            return {"fn": "", "kw": kw}
        return None

    def _site_from(self, node: ast.Call, func: str, kind: str,
                   info: dict) -> JitSite:
        names = ()
        nums = ()
        if "static_argnames" in info["kw"]:
            names = _const_str_tuple(info["kw"]["static_argnames"])
        if "static_argnums" in info["kw"]:
            nums = _const_int_tuple(info["kw"]["static_argnums"])
        return JitSite(path=self.ctx.relpath, line=node.lineno,
                       func=func or info["fn"], kind=kind,
                       static_names=names, static_nums=nums)

    def _scan(self) -> None:
        seen: set = set()
        # decorators: @jax.jit / @functools.partial(jax.jit, ...)
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    info = self._jit_call_info(dec)
                    if info is None:
                        continue
                    seen.add(dec)
                    kind = ("factory"
                            if self.ctx.enclosing_function(node) is not None
                            else "decorator")
                    self.sites.append(self._site_from(dec, node.name,
                                                      kind, info))
                elif self.ctx.dotted(dec) in ("jax.jit", "jax.pmap",
                                              "jit"):
                    kind = ("factory"
                            if self.ctx.enclosing_function(node) is not None
                            else "decorator")
                    self.sites.append(JitSite(
                        path=self.ctx.relpath, line=node.lineno,
                        func=node.name, kind=kind))
        # expression calls: jax.jit(fn, static_argnums=...) AND bare
        # partial(jax.jit, ...) factories outside decorator position
        # (assignment forms recompile just like decorators do)
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call) or node in seen:
                continue
            info = self._jit_call_info(node)
            if info is None:
                continue
            self.sites.append(self._site_from(node, "", "call", info))


def _resolve_bounds(sites: List[JitSite],
                    extra_domains: Optional[Dict[str, Tuple[int, str]]]
                    = None) -> None:
    domains = dict(DOMAINS)
    if extra_domains:
        domains.update(extra_domains)
    for s in sites:
        bound = 1
        for name in s.static_names:
            if name in domains:
                bound *= max(domains[name][0], 1)
            else:
                s.unbounded.append(name)
        for num in s.static_nums:
            key = (os.path.basename(s.path), s.func, num)
            if key in SITE_DOMAINS:
                bound *= max(SITE_DOMAINS[key][0], 1)
            else:
                s.unbounded.append("argnum:%d" % num)
        s.bound = bound


def serve_ladder_bound(min_batch: int = 256,
                       max_batch: int = 65536) -> int:
    """The BatchServer compile bound (generalizes the PR 4 serve-ladder
    audit): every batch in [1, max] maps into <= log2(max/min)+1 pow2
    buckets, each compiling once."""
    return int(np.log2(max(max_batch // max(min_batch, 1), 1))) + 1


def mm_ladder_bound() -> int:
    """The multimodel batch-axis compile bound: the vmapped drivers take
    NO static args (B and k are inferred from argument shapes), so their
    only compile axis is the leading model-axis extent — and
    ``multimodel.driver.bucket_for`` pads every batch up to a power-of-two
    bucket in [MM_MIN_BUCKET, MM_MAX_BUCKET] (wider sweeps chunk at the
    cap), so a run sees at most log2(max/min)+1 distinct batch shapes per
    program family. The value domain of the model-batch static axis, in
    ladder form — the exact analog of :func:`serve_ladder_bound`."""
    from ..multimodel.driver import MM_MAX_BUCKET, MM_MIN_BUCKET
    return int(np.log2(max(MM_MAX_BUCKET // max(MM_MIN_BUCKET, 1), 1))) + 1


def iter_jit_sites(config: Optional[GraftlintConfig] = None
                   ) -> List[JitSite]:
    config = config or load_config()
    sites: List[JitSite] = []
    for root in AUDIT_ROOTS:
        ap = os.path.join(config.root, root)
        if not os.path.isdir(ap):
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      config.root).replace(os.sep, "/")
                with open(os.path.join(config.root, rel), "r",
                          encoding="utf-8") as f:
                    src = f.read()
                scan = _ModuleScan(ModuleContext(src, rel, config))
                sites.extend(scan.sites)
    _resolve_bounds(sites)
    return sites


def analyze_source(source: str, relpath: str,
                   config: Optional[GraftlintConfig] = None
                   ) -> List[JitSite]:
    """Audit one in-memory module (the fixture-test entry point)."""
    config = config or GraftlintConfig()
    scan = _ModuleScan(ModuleContext(source, relpath, config))
    _resolve_bounds(scan.sites)
    return scan.sites


def check_fixture(source: str) -> List[str]:
    """Uniform fixture hook: unbounded-static findings for a snippet."""
    sites = analyze_source(source, "lightgbm_tpu/ops/fixture.py")
    return ["%s:%d static arg `%s` has no registered domain"
            % (s.path, s.line, name)
            for s in sites for name in s.unbounded]


def compile_surface(config: Optional[GraftlintConfig] = None,
                    artifact=None) -> dict:
    """The full surface: sites, the analytic total, the serve ladder."""
    sites = artifact if artifact is not None else iter_jit_sites(config)
    ladder = serve_ladder_bound()
    mm_ladder = mm_ladder_bound()
    total = sum(s.bound for s in sites) + ladder + mm_ladder
    return {"sites": [s.to_dict() for s in sites],
            "serve_ladder_bound": ladder,
            "mm_ladder_bound": mm_ladder,
            # each serving registry slot owns a TPUPredictor instance
            # (its own executable cache), so a multi-model deployment
            # spends `ladder` compiles PER ACTIVE SLOT — per-slot cost
            # for capacity planning; the analytic ceiling stays a
            # single-model-surface bound
            "serving_ladder_per_slot": ladder,
            "total_bound": total}


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    """The gate entry point: one AuditResult over the whole surface.

    ``artifact`` takes a precomputed :func:`iter_jit_sites` list so the
    --json CLI path enumerates the surface once, not twice."""
    config = config or load_config()
    sites = artifact if artifact is not None else iter_jit_sites(config)
    ladder = serve_ladder_bound()
    mm_ladder = mm_ladder_bound()
    total = sum(s.bound for s in sites) + ladder + mm_ladder
    ceiling = int(getattr(config, "compile_ceiling", 64))
    unbounded = [(s, n) for s in sites for n in s.unbounded]
    telemetry.count(C_ENTRIES, len(sites), category="analysis")
    telemetry.count(C_BOUND, total, category="analysis")
    if unbounded:
        telemetry.count(C_UNBOUNDED, len(unbounded), category="analysis")
    if unbounded:
        detail = "; ".join(
            "%s:%d `%s` static arg `%s` has no registered domain "
            "(unbounded recompiles)" % (s.path, s.line, s.func, n)
            for s, n in unbounded[:3])
        ok = False
    elif total > ceiling:
        detail = ("analytic compile bound %d exceeds ceiling %d"
                  % (total, ceiling))
        ok = False
    else:
        detail = ("%d jit sites, compile bound %d <= ceiling %d "
                  "(serve ladder %d, mm ladder %d)"
                  % (len(sites), total, ceiling, ladder, mm_ladder))
        ok = True
    return [AuditResult(name="compile_surface", ok=ok, detail=detail)]
