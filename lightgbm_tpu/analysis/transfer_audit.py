"""Transfer auditor: device programs must stay on the device, sharded.

ROADMAP items 2 and 3 push the persist/level/scan programs to pod
scale, where two silent program shapes turn a compiled hot loop into a
host-bound or HBM-bound one:

* an **implicit device<->host transfer** — a callback / infeed /
  ``device_put`` materializing inside a compiled program serializes the
  pipeline at host speed (the legacy jaxpr audit only checked loop
  *bodies*; a transfer anywhere in a persist program is a per-launch
  stall);
* an **unsharded intermediate** — a value whose sharding degrades to
  replicated above a size threshold multiplies its HBM cost by the
  mesh size and usually rides an ``all_gather`` that DCN pays for.

Both are structural program properties the :mod:`dataflow` engine
records while abstract-evaluating the traced programs: transfer
primitives at any loop depth (alias-semantics ``device_put`` const
staging is benign and marked as such), and explicit replication
collectives (``all_gather``) whose output exceeds
:data:`REPLICATED_BYTES`.  The CPU-traced persist/level/scan and
predict programs must show ZERO of both — the sharded multihost
programs keep their collectives in the host-side guarded DCN layer
(see ``collective_audit``), never inside the compiled level program.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import events as telemetry
from . import dataflow, precision_audit
from .config import GraftlintConfig
from .jaxpr_audit import AuditResult

C_TRANSFERS = "analysis::transfer_sites"

# a replicated intermediate below 1MB is noise; above it, the copy is
# real HBM and real DCN on every mesh participant
REPLICATED_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# audited programs
# ---------------------------------------------------------------------------

def _persist_programs() -> List[Tuple[str, object]]:
    from ..ops.pallas_compat import HAS_PALLAS
    if not HAS_PALLAS:
        return []

    def build():
        from ..ops.pallas_grow import make_level_pass, make_split_pass
        WPA, NP, G, nbw = 8, 1024, 2, 2
        plan = ((0, 0, 255), (1, 0, 255))
        i32 = jnp.int32
        sp = make_split_pass(WPA, NP, G, plan, nbw, C=256)
        closed_sp = jax.make_jaxpr(sp)(
            jax.ShapeDtypeStruct((WPA, NP), jnp.uint32),
            jax.ShapeDtypeStruct((16,), i32))
        S_max, T_max = 4, 16
        lp = make_level_pass(WPA, NP, G, plan, nbw, S_max, T_max,
                             C=256)
        closed_lp = jax.make_jaxpr(lp)(
            jax.ShapeDtypeStruct((WPA, NP), jnp.uint32),
            jax.ShapeDtypeStruct((S_max, 16), i32),
            jax.ShapeDtypeStruct((T_max,), i32),
            jax.ShapeDtypeStruct((S_max,), i32),
            jax.ShapeDtypeStruct((), i32))
        return [("persist_split_pass", closed_sp),
                ("persist_level_pass", closed_lp)]

    return precision_audit._memo("transfer_persist", build)


def _shared_programs() -> List[Tuple[str, object]]:
    """scan_pair + predict, traced ONCE per process and shared with
    the precision-flow auditor (same memo — see precision_audit)."""
    from ..ops.pallas_compat import HAS_PALLAS
    progs = []
    if HAS_PALLAS:
        progs += precision_audit._memo(
            "scan_pair", precision_audit._scan_pair_program)
    progs += precision_audit._memo(
        "predict", precision_audit._predict_program)
    return [(name, closed) for name, closed, _rng, _bless in progs]


def _fused_iteration_programs() -> List[Tuple[str, object]]:
    """The whole-iteration persist drivers (PR 17) — gbdt k-batch scan
    and the RF variant, same memoized traces as
    jaxpr_audit.audit_fused_iteration: a transfer anywhere between
    tree boundaries is a per-batch host stall on the fused fast
    path."""
    from .jaxpr_audit import build_fused_iteration_programs
    art = precision_audit._memo("fused_drivers",
                                build_fused_iteration_programs)
    return list(art["programs"])


# fixture programs ----------------------------------------------------------

def _callback_in_scan():
    """Seeded violation: a host callback inside a scan body — the
    per-level host round-trip the persist design exists to avoid."""
    def prog(x):
        def body(c, _):
            v = jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct((), x.dtype), c[0])
            return c + v, None
        return jax.lax.scan(body, x, None, length=64)[0]

    return [("callback_in_scan", jax.make_jaxpr(prog)(
        jax.ShapeDtypeStruct((4,), jnp.float32)))]


def _clean_scan():
    def prog(x):
        def body(c, _):
            return c * jnp.float32(0.5) + jnp.float32(1.0), None
        return jax.lax.scan(body, x, None, length=64)[0]

    return [("clean_scan", jax.make_jaxpr(prog)(
        jax.ShapeDtypeStruct((4,), jnp.float32)))]


def _all_gather_large():
    """Seeded violation: an in-program all_gather materializing a
    256KB replicated copy on every participant — over the fixture
    threshold, under a lax one (the fixture hook passes its own)."""
    fn = jax.pmap(lambda x: jax.lax.all_gather(x, "i"), axis_name="i")
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((1, 1 << 16), jnp.float32))
    return [("all_gather_large", closed)]


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def _violations(name: str, closed,
                threshold: int = REPLICATED_BYTES) -> List[str]:
    rep = dataflow.interpret(closed, replicated_threshold=threshold)
    out = []
    for t in rep.transfers:
        if t.benign:
            continue
        out.append("%s: implicit device<->host transfer (%s)"
                   % (name, t.describe()))
    for prim, nbytes, depth in rep.replicated_large:
        out.append("%s: %s materializes a replicated %.1fMB "
                   "intermediate (loop depth %d) — shard it or move "
                   "the exchange to the guarded DCN layer"
                   % (name, prim, nbytes / float(1 << 20), depth))
    return out


def compute_artifact(config: Optional[GraftlintConfig] = None) -> dict:
    programs = _persist_programs() + _shared_programs() \
        + _fused_iteration_programs()
    violations: List[str] = []
    for name, closed in programs:
        violations += _violations(name, closed)
    return {"programs": [n for n, _ in programs],
            "violations": violations}


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    name = "transfer"
    try:
        art = artifact if isinstance(artifact, dict) \
            else compute_artifact(config)
    except Exception as e:      # pragma: no cover - defensive
        return [AuditResult(name=name, ok=False,
                            detail="auditor raised: %r" % e)]
    if art["violations"]:
        telemetry.count(C_TRANSFERS, len(art["violations"]),
                        category="analysis")
    return [AuditResult(
        name=name, ok=not art["violations"],
        detail="; ".join(art["violations"][:3]) if art["violations"]
        else "%d program(s) transfer-free with no replicated "
             "intermediate over %dMB"
             % (len(art["programs"]), REPLICATED_BYTES >> 20))]


def check_fixture(payload: dict) -> List[str]:
    """Uniform fixture hook: {"program": "callback_in_scan" |
    "clean_scan" | "all_gather_large"[, "threshold": bytes]}."""
    program = payload["program"]
    threshold = int(payload.get("threshold", REPLICATED_BYTES))
    if program == "all_gather_large":
        progs = _all_gather_large()
    elif program == "callback_in_scan":
        progs = _callback_in_scan()
    else:
        progs = _clean_scan()
    out: List[str] = []
    for name, closed in progs:
        out += _violations(name, closed, threshold=threshold)
    return out
