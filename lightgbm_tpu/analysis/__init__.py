"""Graft-lint: JAX-aware static analysis + jaxpr audit gate.

The repo's two worst defect classes — silent f32->f64 dtype promotion
(the persist-f32 vs v1-f64 tie-flip family pinned by
tests/test_known_divergence.py) and recompile/host-sync hazards on the
serving path — are invisible to pytest until they bite at scale. This
package machine-checks them on every run:

* :mod:`lint` — an AST rule engine (rules JG001-JG012, see
  :mod:`rules`) scanning the package for JAX/TPU pitfalls specific to
  this codebase, with inline suppressions, a checked-in baseline for
  grandfathered findings, and an autofix mode (unused imports).
* :mod:`dataflow` — a reusable abstract interpreter over closed
  jaxprs propagating dtype, interval value-range (seeded from the ops
  modules' ``*_input_contract`` annotations), and accumulated error
  bounds through every primitive including all sub-jaxpr carriers
  (``pjit``/``scan``/``while``/``cond``/``custom_jvp``/``closed_call``)
  with a fixpoint for loop bodies — the shared engine the jaxpr audits
  and the precision/transfer/quant auditors run on.
* :mod:`jaxpr_audit` — traces the real TPU entry points
  (``hist_window``, ``scan_pair``/``scan_blocks``, the persist
  ``split_pass``, the predict traversal) with abstract inputs and
  asserts structural invariants on the jaxpr: no f64 values OR consts
  anywhere in persist-f32 kernels (including consts closed over inside
  call primitives — the class the pre-dataflow walk missed), no host
  callbacks/transfers inside ``fori_loop``/``scan`` bodies, donation
  actually recorded, the serve ladder's compile bound.
* :mod:`strict` — the strict-numerics test harness (strict dtype
  promotion + debug-nans) the kernel-parity tests run under.
* the whole-program auditors (:mod:`auditors` registry):
  :mod:`collective_audit` verifies every rank-role issues the same DCN
  collective sequence (a collective under a rank-dependent branch is a
  deadlock finding) and that every site rides the resilience retry
  guard (lint twin: rule JG009); :mod:`resource_audit` computes static
  per-kernel VMEM footprints and per-shape HBM tallies over the bench
  geometries against the :mod:`telemetry.devices` profiles;
  :mod:`compile_audit` bounds the distinct-compile count across the
  jitted entry points and fails on unbounded static args;
  :mod:`precision_audit` requires every float narrowing in the traced
  ops/predict programs to be blessed (``NARROW_OK``) or range-proven
  on the dataflow engine (lint twin: JG010); :mod:`transfer_audit`
  forbids implicit device<->host transfers and oversized replicated
  intermediates in the persist/level/scan/predict programs;
  :mod:`quant_audit` statically bounds the split-gain / leaf-output
  error of the declared int8/int16/f16 quantization specs and ships
  the ``quant_certificate`` artifact in ``--json``;
  :mod:`concurrency_audit` discovers every thread root in the threaded
  host layer (serving / predict-serve / resilience / telemetry),
  infers per-site lock sets for all shared mutable state
  (lint twins: JG011 unguarded mutation, JG012 blocking call under a
  held lock), keeps the global lock-acquisition-order graph acyclic,
  and ships the per-root abstract trace as ``concurrency_trace`` in
  ``--json``.

Gate: ``python -m lightgbm_tpu.analysis`` exits non-zero on any
unsuppressed finding or failed audit; ``tests/test_analysis.py`` runs
the same self-scan inside the tier-1 suite.
"""
from __future__ import annotations

from .auditors import all_auditors, run_all as run_auditors
from .config import GraftlintConfig, load_config
from .core import Finding
from .jaxpr_audit import AuditResult, run_audits
from .lint import LintReport, prune_baseline, run_lint
from .strict import strict_numerics

__all__ = [
    "AuditResult",
    "Finding",
    "GraftlintConfig",
    "LintReport",
    "all_auditors",
    "load_config",
    "prune_baseline",
    "run_auditors",
    "run_audits",
    "run_lint",
    "strict_numerics",
]
