"""Concurrency auditor: lock discipline for the threaded host layer.

PRs 12 and 16 made the host layer genuinely multi-threaded — the
``AsyncBatchServer`` condition-wait service loop with per-request
futures, ``ModelRegistry`` hot-swap under live load, the straggler
watchdog in ``resilience/retry.py``, and the process-global telemetry
registries (``events.py`` counters, ``histo.py`` histograms, the
``flight.py`` ring) fed from every one of those threads. This module
statically certifies that layer the way ``collective_audit`` certifies
DCN ordering: AST only — no threads are started, no devices touched.

Three analyses per module in the configured ``concurrency_paths``:

* **thread-root discovery** — every ``threading.Thread(target=...)`` /
  ``Timer`` spawn, plus escaping callbacks (a function handed to
  another call or installed by a decorator runs on whoever holds the
  reference — the flight-recorder sinks, the atexit report), plus the
  implicit ``main`` root (the module's public surface). Each root gets
  a reachable-call-graph closure (the ``collective_audit`` fixpoint
  idiom, one intra-module hop per edge).

* **lock-discipline inference** — the shared mutable inventory is the
  module-level mutables plus instance attributes of lock-owning
  classes; every non-blessed write site must hold a consistent lock
  set. Locks are tracked lexically (``with self._lock`` /
  ``with _lock``) and through ONE call level (a helper whose every
  call site holds L is analyzed as holding L — ``_swap_locked``).
  Blessed without a lock: writes inside ``__init__`` (pre-publication),
  single-reference publishes (a plain ``name = value`` rebind is one
  atomic store under the GIL), the GIL-atomic method table
  (``deque.append``/``popleft``, ``set.add``, ``list.append``,
  ``dict.setdefault``, plain subscript stores), and sites carrying a
  ``# guarded-by: <lock|root|GIL>`` annotation — the documented-
  invariant escape hatch, validated against the module's lock and root
  inventory so a typo is itself a finding. Everything else unguarded
  is a finding (lint twin: rule JG011).

* **blocking-hold + lock order** — a lock held across a blocking
  operation (``time.sleep``, ``join``, a future ``.result()``, a
  ``wait`` on a foreign object, device syncs like
  ``block_until_ready``/``finalize_padded``, a retry-guarded
  collective) serializes every thread behind a slow operation, or
  deadlocks outright; each such site is a finding (lint twin: JG012;
  ``Condition.wait`` on the very lock being held is the sanctioned
  pattern and stays silent). Every lock acquisition nested inside
  another contributes an edge to the global lock-acquisition-order
  graph — including cross-module edges through the telemetry entry
  points (``events.count`` takes the events lock, ``histo.observe``
  the histo lock, ``flight.note`` the flight ring lock) — and that
  graph must be cycle-free. A plain ``Lock`` re-acquired while already
  held is reported as a self-deadlock.

A module with no thread spawns and no lock objects is out of scope by
construction — owning a lock or starting a thread is how code declares
concurrent intent, and only declared-concurrent modules are audited.

The per-root abstract trace (roots, shared-site table, lock-order
edges) ships in the CLI's ``--json`` payload as ``concurrency_trace``,
the way ``collective_trace`` does today. Counters:
``analysis::concurrency_roots`` / ``shared_sites`` / ``unguarded`` /
``hold_blocking``.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .core import ModuleContext
from .jaxpr_audit import AuditResult

C_ROOTS = "analysis::concurrency_roots"
C_SHARED = "analysis::shared_sites"
C_UNGUARDED = "analysis::unguarded"
C_HOLD = "analysis::hold_blocking"

# threading spawn constructors -> which argument names the root callable
_THREAD_CTORS = {"Thread": "target", "Timer": "function"}

# lock-object constructors (threading.*); Condition wraps an RLock, so
# re-entry through it is legal — only a plain Lock self-nests fatally
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock", "Condition"}

# fallback: a with-context whose final attribute looks like a lock is
# treated as one even when its constructor is out of sight (a lock
# passed in as a parameter)
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|cond|condition|mutex)$", re.I)

# mutating container/object methods (non-exhaustive on purpose: only
# what the audited layer actually uses)
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "remove", "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "add", "discard", "record", "merge", "sort",
             "reverse"}

# GIL-atomic single-bytecode mutations, blessed without a lock, keyed
# by the container kind inferred from the defining assignment
_ATOMIC_METHODS = {
    "deque": {"append", "appendleft", "pop", "popleft", "clear"},
    "set": {"add", "discard"},
    "list": {"append"},
    "dict": {"setdefault"},
    "defaultdict": {"setdefault"},
}

# blocking operations by final attribute / callable name: holding a
# lock across any of these is JG012. `wait` on the HELD lock itself
# (Condition.wait releases it) is the sanctioned exception.
_BLOCKING = {
    "sleep": "time.sleep",
    "join": "thread join",
    "result": "future result",
    "wait": "wait",
    "acquire": "nested blocking acquire",
    "block_until_ready": "device sync",
    "device_wait": "device sync",
    "finalize_padded": "device sync",
    "predict_padded": "device sync",
    "guard": "retry-guarded collective",
    "process_allgather": "DCN collective",
    "broadcast_one_to_all": "DCN collective",
    "sync_global_devices": "DCN collective",
}

# cross-module lock identity of the telemetry entry points: calling one
# of these while holding a lock contributes a lock-order edge into the
# named module's registry lock
_EXTERNAL_LOCKS = {
    "telemetry.events": ("lightgbm_tpu/telemetry/events.py::_lock",
                         {"count", "add", "scope", "record_iteration",
                          "snapshot", "snapshot_full", "counts_snapshot",
                          "category_totals", "events_snapshot", "reset",
                          "clear_counts_prefix", "set_flight_sinks"}),
    "telemetry.histo": ("lightgbm_tpu/telemetry/histo.py::_lock",
                        {"observe", "merge_counts", "get",
                         "histograms_snapshot", "saturation_total",
                         "reset", "reset_prefix"}),
    "telemetry.flight": ("lightgbm_tpu/telemetry/flight.py::_lock",
                         {"note", "dump", "arm", "disarm", "reset",
                          "snapshot"}),
}

#   x += 1          # guarded-by: ClassName._lock
#   # guarded-by: GIL (single-writer: serving-loop)   (line above works)
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_.\-]+)")


@dataclass
class ThreadRoot:
    """One concurrent entry into a module: a spawned thread, an
    escaping callback, or the implicit main (public-API) root."""

    name: str                       # root label ("main", target qualname)
    kind: str                       # thread | timer | callback | main
    path: str
    line: int                       # spawn/registration site (0 = main)
    reach: Tuple[str, ...] = ()     # reachable function qualnames
    cond_wait: bool = False         # reach contains a condition-wait loop

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind, "path": self.path,
                "line": self.line, "reach": sorted(self.reach),
                "cond_wait": self.cond_wait}


@dataclass
class SharedSite:
    """One write access to a piece of shared mutable state."""

    obj: str                        # "_counts" | "AsyncBatchServer._depth"
    path: str
    line: int
    func: str                       # enclosing function qualname
    access: str                     # augassign | assign | subscript | ...
    locks: Tuple[str, ...] = ()     # lock set held (incl. inherited)
    blessed: str = ""               # "" | init | publish | atomic | guarded-by:<x>
    roots: Tuple[str, ...] = ()     # roots reaching the enclosing func

    def to_dict(self) -> dict:
        return {"obj": self.obj, "path": self.path, "line": self.line,
                "func": self.func, "access": self.access,
                "locks": list(self.locks), "blessed": self.blessed,
                "roots": list(self.roots)}


@dataclass
class ConcFinding:
    """One lock-discipline / blocking-hold / lock-order hazard."""

    rule: str                       # JG011 | JG012 | lock-order
    path: str
    line: int
    func: str
    message: str
    node: Optional[ast.AST] = field(default=None, repr=False,
                                    compare=False)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "func": self.func, "message": self.message}


class _ModuleConcurrency:
    """Roots + shared-site table + findings for one parsed module."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.roots: List[ThreadRoot] = []
        self.shared: List[SharedSite] = []
        self.findings: List[ConcFinding] = []
        # lock-order edges: (outer lock id, inner lock id, line)
        self.lock_edges: List[Tuple[str, str, int]] = []
        self.locks: Dict[str, str] = {}       # lock id -> ctor name
        self.concurrent = False
        self._funcs: Dict[str, ast.AST] = {}  # qualname -> def node
        self._func_of_node: Dict[ast.AST, str] = {}
        self._calls: Dict[str, Set[str]] = {}
        self._inherited: Dict[str, Set[str]] = {}
        self._main_reach: Set[str] = set()
        self._root_reach: Dict[str, Set[str]] = {}
        self._globals: Dict[str, str] = {}    # name -> container kind
        self._attr_kind: Dict[str, str] = {}  # "Cls.attr" -> kind
        self._run()

    # -- structure ------------------------------------------------------
    def _qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.ctx.parent.get(cur)
        return ".".join(reversed(parts))

    def _owner_class(self, node: ast.AST) -> Optional[str]:
        cur = self.ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.ctx.parent.get(cur)
        return None

    def _collect_functions(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._funcs[self._qualname(node)] = node
        for qn, fn in self._funcs.items():
            for sub in ast.walk(fn):
                if self.ctx.enclosing_function(sub) is fn:
                    self._func_of_node[sub] = qn

    def _enclosing_qualname(self, node: ast.AST) -> str:
        fn = self.ctx.enclosing_function(node)
        while isinstance(fn, ast.Lambda):
            fn = self.ctx.enclosing_function(fn)
        if fn is None:
            return ""
        return self._qualname(fn)

    # -- locks ----------------------------------------------------------
    def _ctor_leaf(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            t = self.ctx.call_target(value)
            if t is not None:
                return t.split(".")[-1]
        return None

    def _collect_locks(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            leaf = self._ctor_leaf(node.value)
            if leaf not in _LOCK_CTORS:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and self.ctx.parent.get(node) is self.ctx.tree:
                    self.locks[t.id] = leaf
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    cls = self._owner_class(node)
                    if cls is not None:
                        self.locks["%s.%s" % (cls, t.attr)] = leaf

    def _lock_of_expr(self, expr: ast.AST) -> Optional[str]:
        """Canonical lock id of a with/wait context expression, or None
        when it is not lock-shaped."""
        d = self.ctx.dotted(expr)
        if d is None:
            return None
        leaf = d.split(".")[-1]
        if d.startswith("self."):
            cls = self._owner_class(expr) or "?"
            lid = "%s.%s" % (cls, d[len("self."):])
        else:
            lid = d
        if lid in self.locks or _LOCK_NAME_RE.search(leaf):
            return lid
        return None

    def _lexical_locks(self, node: ast.AST) -> List[Tuple[str, ast.With]]:
        """Locks held lexically at `node` (innermost last), stopping at
        the enclosing function boundary."""
        held: List[Tuple[str, ast.With]] = []
        cur = self.ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    lid = self._lock_of_expr(item.context_expr)
                    if lid is not None:
                        held.append((lid, cur))
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            cur = self.ctx.parent.get(cur)
        held.reverse()
        return held

    def _locks_at(self, node: ast.AST) -> Set[str]:
        held = {lid for lid, _ in self._lexical_locks(node)}
        held |= self._inherited.get(self._enclosing_qualname(node), set())
        return held

    def _compute_inherited(self) -> None:
        """One-call-level lock propagation: a module function whose
        EVERY call site holds lock L is analyzed as holding L
        (``_swap_locked``); functions never called intra-module (or
        handed to a thread/callback) inherit nothing."""
        sites: Dict[str, List[Set[str]]] = {}
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve_callee(node)
            if callee is None:
                continue
            sites.setdefault(callee, []).append(
                {lid for lid, _ in self._lexical_locks(node)})
        for qn, lock_sets in sites.items():
            common = set.intersection(*lock_sets) if lock_sets else set()
            if common:
                self._inherited[qn] = common

    # -- call graph -----------------------------------------------------
    def _resolve_callee(self, call: ast.Call) -> Optional[str]:
        """Qualname of a same-module callee: a bare name (preferring a
        sibling nested def), or a ``self.m`` method of the enclosing
        class."""
        f = call.func
        if isinstance(f, ast.Name):
            enclosing = self._enclosing_qualname(call)
            if enclosing:
                nested = "%s.%s" % (enclosing, f.id)
                if nested in self._funcs:
                    return nested
            if f.id in self._funcs:
                return f.id
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            cls = self._owner_class(call)
            if cls is not None and "%s.%s" % (cls, f.attr) in self._funcs:
                return "%s.%s" % (cls, f.attr)
        return None

    def _build_call_graph(self) -> None:
        for qn, fn in self._funcs.items():
            out: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and self._func_of_node.get(sub) == qn:
                    callee = self._resolve_callee(sub)
                    if callee is not None:
                        out.add(callee)
            self._calls[qn] = out

    def _reach(self, start: str) -> Set[str]:
        seen: Set[str] = set()
        frontier = [start]
        while frontier:
            qn = frontier.pop()
            if qn in seen or qn not in self._funcs:
                continue
            seen.add(qn)
            frontier.extend(self._calls.get(qn, ()))
        return seen

    # -- roots ----------------------------------------------------------
    def _resolve_func_ref(self, expr: ast.AST,
                          at: ast.AST) -> Optional[str]:
        """A Name/Attribute expression that references a same-module
        function (``target=self._loop`` / ``target=run``)."""
        if isinstance(expr, ast.Name):
            enclosing = self._enclosing_qualname(at)
            if enclosing and "%s.%s" % (enclosing, expr.id) in self._funcs:
                return "%s.%s" % (enclosing, expr.id)
            if expr.id in self._funcs:
                return expr.id
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            cls = self._owner_class(at)
            if cls is not None \
                    and "%s.%s" % (cls, expr.attr) in self._funcs:
                return "%s.%s" % (cls, expr.attr)
        return None

    def _has_cond_wait(self, reach: Set[str]) -> bool:
        for qn in reach:
            fn = self._funcs.get(qn)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "wait":
                    return True
        return False

    def _discover_roots(self) -> None:
        targeted: Set[str] = set()
        # spawned threads / timers
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t = self.ctx.call_target(node)
            leaf = (t or "").split(".")[-1]
            if leaf not in _THREAD_CTORS:
                continue
            target_expr = None
            for kw in node.keywords:
                if kw.arg == _THREAD_CTORS[leaf]:
                    target_expr = kw.value
            if target_expr is None and leaf == "Timer" \
                    and len(node.args) >= 2:
                target_expr = node.args[1]
            qn = (self._resolve_func_ref(target_expr, node)
                  if target_expr is not None else None)
            name = qn or (self.ctx.dotted(target_expr)
                          if target_expr is not None else None) \
                or "<unresolved>"
            reach = self._reach(qn) if qn else set()
            self.roots.append(ThreadRoot(
                name=name, kind="thread" if leaf == "Thread" else "timer",
                path=self.ctx.relpath, line=node.lineno,
                reach=tuple(sorted(reach)),
                cond_wait=self._has_cond_wait(reach)))
            if qn:
                targeted.add(qn)
        # escaping callbacks: a function handed to another call as an
        # argument, or installed by a decorator (atexit.register) — it
        # runs on whichever thread ends up holding the reference
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Call):
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    qn = self._resolve_func_ref(arg, node)
                    if qn and qn not in targeted:
                        targeted.add(qn)
                        reach = self._reach(qn)
                        self.roots.append(ThreadRoot(
                            name=qn, kind="callback",
                            path=self.ctx.relpath, line=node.lineno,
                            reach=tuple(sorted(reach)),
                            cond_wait=self._has_cond_wait(reach)))
        for qn, fn in self._funcs.items():
            for dec in getattr(fn, "decorator_list", []):
                d = self.ctx.dotted(dec.func if isinstance(dec, ast.Call)
                                    else dec)
                if d is not None and d.split(".")[-1] == "register" \
                        and qn not in targeted:
                    targeted.add(qn)
                    reach = self._reach(qn)
                    self.roots.append(ThreadRoot(
                        name=qn, kind="callback", path=self.ctx.relpath,
                        line=fn.lineno, reach=tuple(sorted(reach)),
                        cond_wait=self._has_cond_wait(reach)))
        for r in self.roots:
            self._root_reach[r.name] = set(r.reach)
        # the implicit main root: the public surface plus its closure
        entries = [qn for qn, fn in self._funcs.items()
                   if (not fn.name.startswith("_")
                       or (fn.name.startswith("__")
                           and fn.name.endswith("__")
                           and fn.name != "__init__"))
                   and "." not in qn.replace(
                       (self._owner_class(fn) or "") + ".", "", 1)]
        main: Set[str] = set()
        for qn in entries:
            main |= self._reach(qn)
        self._main_reach = main
        self.roots.append(ThreadRoot(
            name="main", kind="main", path=self.ctx.relpath, line=0,
            reach=tuple(sorted(main)),
            cond_wait=self._has_cond_wait(main)))

    def _roots_of(self, qualname: str) -> Tuple[str, ...]:
        out = [r.name for r in self.roots if r.kind != "main"
               and qualname in self._root_reach.get(r.name, ())]
        if qualname in self._main_reach or qualname == "" or not out:
            out.append("main")
        return tuple(out)

    # -- self-concurrency ----------------------------------------------
    def _self_concurrent(self, owner: str) -> bool:
        """A lock-owning class (or a module with a module-level lock)
        declares that its public surface is called from multiple
        threads — its main root is concurrent with itself."""
        if owner:
            return any(lid.startswith(owner + ".") for lid in self.locks)
        return any("." not in lid for lid in self.locks)

    # -- shared-state inventory ----------------------------------------
    def _container_kind(self, value: ast.AST) -> str:
        leaf = self._ctor_leaf(value)
        if leaf in ("deque", "set", "dict", "list", "defaultdict",
                    "frozenset", "Counter", "OrderedDict"):
            return {"frozenset": "set", "Counter": "dict",
                    "OrderedDict": "dict"}.get(leaf, leaf)
        if isinstance(value, ast.Dict):
            return "dict"
        if isinstance(value, (ast.List, ast.ListComp)):
            return "list"
        if isinstance(value, (ast.Set, ast.SetComp)):
            return "set"
        return ""

    def _collect_inventory(self) -> None:
        for node in self.ctx.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            for t in targets:
                if t.id in self.locks:
                    continue
                self._globals[t.id] = self._container_kind(value)
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._note_attr(t, node.value)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                self._note_attr(node.target, node.value)

    def _note_attr(self, target: ast.AST, value: Optional[ast.AST]) -> None:
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            cls = self._owner_class(target)
            if cls is None:
                return
            key = "%s.%s" % (cls, target.attr)
            if key in self.locks:
                return
            kind = (self._container_kind(value)
                    if value is not None else "")
            if key not in self._attr_kind or kind:
                self._attr_kind[key] = kind

    def _func_globals(self, qualname: str) -> Set[str]:
        fn = self._funcs.get(qualname)
        out: Set[str] = set()
        if fn is None:
            return out
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Global) \
                    and self._func_of_node.get(sub) == qualname:
                out.update(sub.names)
        return out

    def _obj_of_expr(self, expr: ast.AST,
                     qualname: str) -> Optional[Tuple[str, str]]:
        """(object key, container kind) when `expr` denotes a tracked
        shared object (a module global or a self attribute)."""
        if isinstance(expr, ast.Name):
            if expr.id in self._globals:
                return expr.id, self._globals[expr.id]
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            cls = self._owner_class(expr)
            if cls is None:
                return None
            key = "%s.%s" % (cls, expr.attr)
            if key in self._attr_kind:
                return key, self._attr_kind[key]
        return None

    # -- blessing -------------------------------------------------------
    def _annotation(self, line: int) -> Optional[str]:
        for ln in (line, line - 1):
            if not (0 < ln <= len(self.ctx.lines)):
                continue
            text = self.ctx.lines[ln - 1]
            if ln == line - 1 and not text.lstrip().startswith("#"):
                continue
            m = _GUARDED_BY_RE.search(text)
            if m:
                return m.group(1)
        return None

    def _known_guards(self) -> Set[str]:
        known = {"GIL"} | set(self.locks)
        known.update(lid.split(".")[-1] for lid in self.locks)
        known.update(r.name for r in self.roots)
        known.update(r.name.split(".")[-1] for r in self.roots)
        return known

    def _bless(self, site_node: ast.AST, qualname: str, access: str,
               kind: str) -> str:
        if qualname.split(".")[-1] == "__init__":
            return "init"
        ann = self._annotation(site_node.lineno)
        if ann is not None:
            if ann not in self._known_guards():
                self.findings.append(ConcFinding(
                    rule="JG011", path=self.ctx.relpath,
                    line=site_node.lineno, func=qualname,
                    message="guarded-by names unknown lock/root %r "
                            "(known: %s)"
                            % (ann, ", ".join(sorted(
                                self._known_guards()))),
                    node=site_node))
            return "guarded-by:%s" % ann
        if access == "assign":
            return "publish"
        if access == "subscript":
            return "atomic"          # one STORE_SUBSCR bytecode
        if access.startswith("method:"):
            meth = access.split(":", 1)[1]
            if meth in _ATOMIC_METHODS.get(kind, ()):
                return "atomic"
        return ""

    # -- write-site walk -----------------------------------------------
    def _add_site(self, obj: str, kind: str, node: ast.AST,
                  access: str) -> None:
        qualname = self._func_of_node.get(node,
                                          self._enclosing_qualname(node))
        locks = self._locks_at(node)
        self.shared.append(SharedSite(
            obj=obj, path=self.ctx.relpath, line=node.lineno,
            func=qualname, access=access, locks=tuple(sorted(locks)),
            blessed=self._bless(node, qualname, access, kind),
            roots=self._roots_of(qualname)))

    def _collect_sites(self) -> None:
        reads: Dict[str, Set[str]] = {}
        for node in ast.walk(self.ctx.tree):
            qualname = self._func_of_node.get(node)
            if qualname is None:
                continue          # module-level statements: main, cold
            fn_globals = self._func_globals(qualname)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._site_for_target(t, node, qualname, fn_globals,
                                          rmw=False)
            elif isinstance(node, ast.AugAssign):
                self._site_for_target(node.target, node, qualname,
                                      fn_globals, rmw=True)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        info = self._obj_of_expr(t.value, qualname)
                        if info is not None:
                            self._add_site(info[0], info[1], node,
                                           "subscript-del")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                info = self._obj_of_expr(node.func.value, qualname)
                if info is not None:
                    self._add_site(info[0], info[1], node,
                                   "method:%s" % node.func.attr)
            elif isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                if isinstance(node, ast.Name) \
                        and node.id in self._globals \
                        and node.id not in fn_globals \
                        and self._assigned_locally(qualname, node.id):
                    continue      # shadowed local, not the module global
                info = self._obj_of_expr(node, qualname)
                if info is not None:
                    reads.setdefault(info[0], set()).update(
                        self._roots_of(qualname))
        self._read_roots = reads

    def _assigned_locally(self, qualname: str, name: str) -> bool:
        fn = self._funcs.get(qualname)
        if fn is None:
            return False
        for sub in ast.walk(fn):
            if self._func_of_node.get(sub) != qualname:
                continue
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(sub.target, ast.Name) \
                    and sub.target.id == name:
                return True
        return False

    def _site_for_target(self, target: ast.AST, stmt: ast.AST,
                         qualname: str, fn_globals: Set[str],
                         rmw: bool) -> None:
        if isinstance(target, ast.Name):
            if target.id in self._globals and target.id in fn_globals:
                self._add_site(target.id, self._globals[target.id],
                               stmt, "augassign" if rmw else "assign")
        elif isinstance(target, ast.Attribute):
            info = self._obj_of_expr(target, qualname)
            if info is not None:
                self._add_site(info[0], info[1], stmt,
                               "augassign" if rmw else "assign")
        elif isinstance(target, ast.Subscript):
            info = self._obj_of_expr(target.value, qualname)
            if info is not None:
                self._add_site(info[0], info[1], stmt,
                               "subscript-rmw" if rmw else "subscript")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._site_for_target(el, stmt, qualname, fn_globals,
                                      rmw)

    # -- lock-discipline verdicts --------------------------------------
    def _check_discipline(self) -> None:
        by_obj: Dict[str, List[SharedSite]] = {}
        for s in self.shared:
            by_obj.setdefault(s.obj, []).append(s)
        for obj, sites in sorted(by_obj.items()):
            owner = obj.rsplit(".", 1)[0] if "." in obj else ""
            roots: Set[str] = set(self._read_roots.get(obj, ()))
            for s in sites:
                roots.update(s.roots)
            multi = len(roots) >= 2 or (
                self._self_concurrent(owner)
                and any("main" in s.roots for s in sites))
            if not multi:
                continue
            live = [s for s in sites if s.blessed != "init"]
            for s in live:
                if s.blessed or s.locks:
                    continue
                self.findings.append(ConcFinding(
                    rule="JG011", path=s.path, line=s.line, func=s.func,
                    message="unguarded mutation of shared `%s` "
                            "(%s; reached from roots: %s): hold its "
                            "lock, or bless with `# guarded-by: <lock>`"
                            % (obj, s.access, ", ".join(sorted(roots))),
                    node=s))
            locked = [set(s.locks) for s in live
                      if s.locks and not s.blessed]
            if len(locked) >= 2 and not set.intersection(*locked):
                first = next(s for s in live
                             if s.locks and not s.blessed)
                self.findings.append(ConcFinding(
                    rule="JG011", path=first.path, line=first.line,
                    func=first.func,
                    message="inconsistent lock sets guarding `%s`: %s "
                            "— sites share no common lock, so they do "
                            "not exclude each other"
                            % (obj, " vs ".join(
                                sorted("{%s}" % ",".join(sorted(ls))
                                       for ls in locked))),
                    node=first))

    # -- blocking-hold --------------------------------------------------
    def _blocking_leaf(self, call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Attribute):
            leaf = call.func.attr
        elif isinstance(call.func, ast.Name):
            leaf = (self.ctx.dotted(call.func) or call.func.id
                    ).split(".")[-1]
        else:
            return None
        return leaf if leaf in _BLOCKING else None

    def _check_blocking(self) -> None:
        blocking_funcs: Set[str] = set()
        direct: List[Tuple[ast.Call, str, Set[str]]] = []
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = self._blocking_leaf(node)
            if leaf is None:
                continue
            held = self._locks_at(node)
            if leaf in ("wait", "acquire") \
                    and isinstance(node.func, ast.Attribute):
                lid = self._lock_of_expr(node.func.value)
                if lid is not None and lid in held:
                    continue      # Condition.wait on the held lock
            qn = self._func_of_node.get(node, "")
            if qn:
                blocking_funcs.add(qn)
            direct.append((node, leaf, held))
        for node, leaf, held in direct:
            if not held:
                continue
            self.findings.append(ConcFinding(
                rule="JG012", path=self.ctx.relpath, line=node.lineno,
                func=self._func_of_node.get(node, ""),
                message="lock(s) {%s} held across blocking %s (`%s`): "
                        "every thread contending for the lock stalls "
                        "behind it — move the blocking call outside "
                        "the critical section"
                        % (",".join(sorted(held)), _BLOCKING[leaf],
                           leaf), node=node))
        # one call level: calling a function that blocks, lock in hand
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve_callee(node)
            if callee is None or callee not in blocking_funcs:
                continue
            held = {lid for lid, _ in self._lexical_locks(node)}
            if not held:
                continue
            self.findings.append(ConcFinding(
                rule="JG012", path=self.ctx.relpath, line=node.lineno,
                func=self._func_of_node.get(node, ""),
                message="lock(s) {%s} held across call to `%s`, whose "
                        "body performs a blocking operation"
                        % (",".join(sorted(held)), callee), node=node))

    # -- lock order -----------------------------------------------------
    def _node_id(self, lock_id: str) -> str:
        return "%s::%s" % (self.ctx.relpath, lock_id)

    def _external_lock(self, call: ast.Call) -> Optional[str]:
        t = self.ctx.call_target(call)
        if t is None:
            return None
        leaf = t.split(".")[-1]
        for frag, (node_id, api) in _EXTERNAL_LOCKS.items():
            if frag in t and leaf in api:
                # the telemetry modules themselves hold their own lock
                # legitimately; only cross-module callers edge into it
                if node_id.split("::")[0] != self.ctx.relpath:
                    return node_id
        return None

    def _collect_lock_edges(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = [self._lock_of_expr(i.context_expr)
                         for i in node.items]
                inner = [lid for lid in inner if lid is not None]
                if not inner:
                    continue
                outer = self._locks_at(node)
                for lid in inner:
                    if lid in outer:
                        if self.locks.get(lid) not in _REENTRANT_CTORS \
                                and lid in self.locks:
                            self.findings.append(ConcFinding(
                                rule="lock-order",
                                path=self.ctx.relpath, line=node.lineno,
                                func=self._func_of_node.get(node, ""),
                                message="non-reentrant lock `%s` "
                                        "re-acquired while already "
                                        "held: self-deadlock" % lid,
                                node=node))
                        continue
                    for o in outer:
                        self.lock_edges.append(
                            (self._node_id(o), self._node_id(lid),
                             node.lineno))
            elif isinstance(node, ast.Call):
                ext = self._external_lock(node)
                if ext is not None:
                    for o in self._locks_at(node):
                        self.lock_edges.append(
                            (self._node_id(o), ext, node.lineno))

    # -- driver ---------------------------------------------------------
    def _run(self) -> None:
        self._collect_functions()
        self._collect_locks()
        self._build_call_graph()
        self._discover_roots()
        self.concurrent = bool(self.locks) or any(
            r.kind in ("thread", "timer") for r in self.roots)
        if not self.concurrent:
            self.roots = []
            return
        self._compute_inherited()
        self._collect_inventory()
        self._collect_sites()
        self._check_discipline()
        self._check_blocking()
        self._collect_lock_edges()


# ---------------------------------------------------------------------------
# cycle detection over the global acquisition-order graph
# ---------------------------------------------------------------------------

def detect_cycles(edges: List[Tuple[str, str, int]]) -> List[List[str]]:
    """Cycles in the lock-order graph (each as the node list of one
    cycle); deterministic order for stable reports."""
    graph: Dict[str, Set[str]] = {}
    for a, b, _line in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    color: Dict[str, int] = {}
    stack: List[str] = []

    def visit(n: str) -> None:
        color[n] = 1
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, 0) == 1:
                cyc = stack[stack.index(m):] + [m]
                key = tuple(sorted(cyc[:-1]))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
            elif color.get(m, 0) == 0:
                visit(m)
        stack.pop()
        color[n] = 2

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            visit(n)
    return cycles


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_source(source: str, relpath: str,
                   config: Optional[GraftlintConfig] = None
                   ) -> _ModuleConcurrency:
    """Audit one in-memory module (the fixture-test entry point)."""
    config = config or GraftlintConfig()
    return _ModuleConcurrency(ModuleContext(source, relpath, config))


def module_findings(ctx: ModuleContext) -> List[ConcFinding]:
    """Per-module findings for the JG011/JG012 lint rules; the analysis
    is cached on the context so both rules share one pass."""
    cached = getattr(ctx, "_concurrency_audit", None)
    if cached is None:
        cached = _ModuleConcurrency(ctx)
        ctx._concurrency_audit = cached
    out = list(cached.findings)
    for cyc in detect_cycles(cached.lock_edges):
        line = min((ln for a, b, ln in cached.lock_edges
                    if a in cyc and b in cyc), default=1)
        out.append(ConcFinding(
            rule="lock-order", path=ctx.relpath, line=line, func="",
            message="lock-acquisition-order cycle: %s — two threads "
                    "taking these locks in opposite orders deadlock"
                    % " -> ".join(c.split("::")[-1] for c in cyc)))
    return out


def check_fixture(source: str) -> List[str]:
    """Uniform fixture hook: concurrency findings for a source snippet
    placed in the serving layer."""
    ctx = ModuleContext(source, "lightgbm_tpu/serving/fixture.py",
                        GraftlintConfig())
    return [f.message for f in module_findings(ctx)]


def _audited_files(config: GraftlintConfig) -> List[str]:
    out: List[str] = []
    for frag in config.concurrency_paths:
        ap = os.path.join(config.root, frag)
        if os.path.isfile(ap):
            out.append(frag)
            continue
        if not os.path.isdir(ap):
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn),
                        config.root).replace(os.sep, "/")
                    out.append(rel)
    return out


def audit_repo(config: Optional[GraftlintConfig] = None
               ) -> Tuple[List[ThreadRoot], List[SharedSite],
                          List[ConcFinding],
                          List[Tuple[str, str, int]]]:
    config = config or load_config()
    roots: List[ThreadRoot] = []
    shared: List[SharedSite] = []
    findings: List[ConcFinding] = []
    edges: List[Tuple[str, str, int]] = []
    for rel in _audited_files(config):
        with open(os.path.join(config.root, rel), "r",
                  encoding="utf-8") as f:
            src = f.read()
        ctx = ModuleContext(src, rel, config)
        audit = _ModuleConcurrency(ctx)
        roots.extend(audit.roots)
        shared.extend(audit.shared)
        # inline suppression works at the gate layer too, so one
        # `# graftlint: disable=JG011` blesses both the lint rule and
        # the auditor verdict (the baseline stays empty either way)
        findings.extend(
            f for f in audit.findings
            if not (f.rule in ("JG011", "JG012")
                    and ctx.is_inline_suppressed(f.rule, f.line)))
        edges.extend(audit.lock_edges)
    return roots, shared, findings, edges


def compute_artifact(config: Optional[GraftlintConfig] = None):
    return audit_repo(config)


def extract_trace(config: Optional[GraftlintConfig] = None,
                  artifact=None) -> dict:
    """The abstract per-root concurrency trace for the --json payload:
    thread roots with their reachable closures, the shared-site table
    with lock sets and blessings, the lock-order graph, findings."""
    roots, shared, findings, edges = artifact if artifact is not None \
        else audit_repo(config)
    return {
        "roots": [r.to_dict() for r in roots],
        "shared_sites": [s.to_dict() for s in shared],
        "lock_order": {
            "edges": sorted({(a, b) for a, b, _ in edges}),
            "cycles": detect_cycles(edges),
        },
        "findings": [f.to_dict() for f in findings],
    }


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    """The gate entry point: three AuditResults (discipline /
    blocking-hold / lock order). ``artifact`` takes a precomputed
    :func:`audit_repo` result so the --json CLI walks once."""
    roots, shared, findings, edges = artifact if artifact is not None \
        else audit_repo(config)
    unguarded = [f for f in findings if f.rule == "JG011"]
    holds = [f for f in findings if f.rule == "JG012"]
    order_findings = [f for f in findings if f.rule == "lock-order"]
    cycles = detect_cycles(edges)
    thread_roots = [r for r in roots if r.kind != "main"]
    telemetry.count(C_ROOTS, len(thread_roots), category="analysis")
    telemetry.count(C_SHARED, len(shared), category="analysis")
    if unguarded:
        telemetry.count(C_UNGUARDED, len(unguarded), category="analysis")
    if holds:
        telemetry.count(C_HOLD, len(holds), category="analysis")
    discipline = AuditResult(
        name="concurrency_discipline",
        ok=not unguarded,
        detail=("%d shared write site(s) across %d root(s), all "
                "guarded or blessed" % (len(shared),
                                        len(thread_roots) or 1))
        if not unguarded else "; ".join(
            "%s:%d %s" % (f.path, f.line, f.message)
            for f in unguarded[:3]))
    blocking = AuditResult(
        name="concurrency_blocking_hold",
        ok=not holds,
        detail="no lock held across a blocking operation"
        if not holds else "; ".join(
            "%s:%d %s" % (f.path, f.line, f.message)
            for f in holds[:3]))
    n_edges = len({(a, b) for a, b, _ in edges})
    order = AuditResult(
        name="concurrency_lock_order",
        ok=not cycles and not order_findings,
        detail=("%d acquisition-order edge(s), acyclic" % n_edges)
        if not cycles and not order_findings else "; ".join(
            ["cycle: %s" % " -> ".join(c.split("::")[-1] for c in cyc)
             for cyc in cycles[:2]]
            + ["%s:%d %s" % (f.path, f.line, f.message)
               for f in order_findings[:2]]))
    return [discipline, blocking, order]
