"""Graft-lint configuration: defaults + the ``[tool.graftlint]`` table.

Python 3.10 has no ``tomllib``, and the package must not grow a toml
dependency (hard constraint: nothing gets pip-installed), so the loader
parses just the subset pyproject actually uses: one ``[tool.graftlint]``
table of ``key = value`` lines where a value is a string, int, bool, or
a (possibly multi-line) list of strings. Anything fancier belongs in
code, not config.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_SECTION = "[tool.graftlint]"


@dataclass
class GraftlintConfig:
    """Knobs for the lint engine; see docs/COMPONENTS.md for semantics."""

    # file selection (path fragments relative to the repo root)
    include: List[str] = field(default_factory=lambda: ["lightgbm_tpu"])
    exclude: List[str] = field(default_factory=lambda: [
        "__pycache__", "lightgbm_tpu/native"])
    # rule ids disabled outright
    disable: List[str] = field(default_factory=list)
    # JG002: host-sync findings only fire inside these path fragments
    hot_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/ops/", "lightgbm_tpu/predict/",
        "lightgbm_tpu/parallel/", "lightgbm_tpu/serving/"])
    # JG001/JG003a: a function whose name matches one of these regexes is
    # treated as TPU kernel code (in addition to jit-decorated functions)
    kernel_names: List[str] = field(default_factory=lambda: [
        r".*_kernel$", r"^kernel$", r"^_fill_(fwd|bwd)$"])
    # JG006: the only modules allowed to import pallas directly
    pallas_compat_allow: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/ops/pallas_compat.py"])
    # JG008: path fragments whose file writes must be atomic
    # (tmp + fsync + os.replace) — the checkpoint/state durability contract
    atomic_write_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/resilience/"])
    # collective-order auditor + JG009: files/dirs holding host-side DCN
    # collective call sites (rank-consistency and guard-wrapping checks)
    collective_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/parallel/", "lightgbm_tpu/resilience/"])
    # mesh-collective trace: files whose IN-PROGRAM labeled collective
    # wrappers (ops/quantize.plane_psum / vote_allgather) are extracted
    # into the collective trace's `mesh_sites` section — the wire-format
    # diff artifact of the quantized-histogram exchange. These run inside
    # jitted SPMD programs (XLA sequences them), so the guard/observed
    # audits do not apply; every site must still carry a literal label.
    mesh_collective_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/ops/grow.py",
        "lightgbm_tpu/ops/grow_persist.py"])
    # JG010: ops//predict/ files whose narrowing casts are blessed —
    # their NARROW_OK tables + input contracts feed the precision-flow
    # auditor; narrowing anywhere else in the hot paths is a finding
    narrow_ok_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/ops/grow.py",
        "lightgbm_tpu/ops/grow_persist.py",
        "lightgbm_tpu/ops/pallas_grow.py",
        "lightgbm_tpu/ops/pallas_histogram.py",
        "lightgbm_tpu/ops/pallas_scan.py",
        "lightgbm_tpu/ops/quantize.py"])
    # concurrency auditor + JG011/JG012: the threaded host layer —
    # modules here that own locks or spawn threads get lock-discipline,
    # blocking-hold, and lock-order analysis
    concurrency_paths: List[str] = field(default_factory=lambda: [
        "lightgbm_tpu/serving/", "lightgbm_tpu/predict/serve.py",
        "lightgbm_tpu/resilience/", "lightgbm_tpu/telemetry/"])
    # resource auditor: device profile the VMEM/HBM budgets come from
    # (telemetry/devices.py; "auto" = detect attached accelerator)
    audit_device: str = "v5e"
    # compile auditor: ceiling on the analytic distinct-compile bound
    compile_ceiling: int = 64
    # perf sentinel (--perf): relative noise band a headline bench key
    # may move within before counting as a regression, when the rounds
    # being compared carry no recorded BENCH_REPEATS spread
    perf_band: float = 0.15
    # baseline suppression file, relative to the repo root
    baseline: str = "lightgbm_tpu/analysis/baseline.json"
    root: str = "."

    def baseline_path(self) -> str:
        return os.path.join(self.root, self.baseline)

    def kernel_regexes(self) -> List["re.Pattern"]:
        return [re.compile(p) for p in self.kernel_names]

    def is_excluded(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(frag in rp for frag in self.exclude)

    def is_hot_path(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(rp.startswith(frag) or frag in rp
                   for frag in self.hot_paths)


def _parse_table(text: str) -> Dict[str, object]:
    """Extract `[tool.graftlint]` key/values from pyproject text."""
    lines = text.splitlines()
    out: Dict[str, object] = {}
    in_section = False
    buf: Optional[Tuple[str, str]] = None   # (key, partial value)
    for line in lines:
        stripped = line.strip()
        if stripped.startswith("["):
            if buf is not None:
                raise ValueError("unterminated graftlint list for %r"
                                 % buf[0])
            in_section = stripped == _SECTION
            continue
        if not in_section or not stripped or stripped.startswith("#"):
            continue
        if buf is not None:
            key, part = buf
            part += " " + stripped
            if _balanced(part):
                out[key] = _parse_value(part)
                buf = None
            else:
                buf = (key, part)
            continue
        m = re.match(r"^([A-Za-z0-9_-]+)\s*=\s*(.+)$", stripped)
        if not m:
            raise ValueError("cannot parse graftlint config line: %r"
                             % stripped)
        key, val = m.group(1).replace("-", "_"), m.group(2).strip()
        if val.startswith("[") and not _balanced(val):
            buf = (key, val)
        else:
            out[key] = _parse_value(val)
    if buf is not None:
        raise ValueError("unterminated graftlint list for %r" % buf[0])
    return out


def _balanced(val: str) -> bool:
    return val.count("[") == val.count("]")


def _parse_value(val: str):
    val = val.strip()
    if val == "true":
        return True
    if val == "false":
        return False
    # strings / lists / ints share Python literal syntax once true/false
    # are gone; strip trailing comments outside quotes first
    try:
        return ast.literal_eval(val)
    except (ValueError, SyntaxError):
        raise ValueError("unsupported graftlint config value: %r" % val)


def load_config(root: Optional[str] = None) -> GraftlintConfig:
    """Config from `<root>/pyproject.toml`'s [tool.graftlint] table,
    defaults when the file or table is absent. `root` defaults to the
    package's repo checkout (the directory holding pyproject.toml)."""
    if root is None:
        here = os.path.dirname(os.path.abspath(__file__))
        root = os.path.dirname(os.path.dirname(here))
    cfg = GraftlintConfig(root=root)
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.isfile(pyproject):
        return cfg
    with open(pyproject, "r", encoding="utf-8") as f:
        table = _parse_table(f.read())
    for key, val in table.items():
        if not hasattr(cfg, key):
            raise ValueError("unknown [tool.graftlint] key: %r" % key)
        setattr(cfg, key, val)
    return cfg
