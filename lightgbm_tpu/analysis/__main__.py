"""Graft-lint CLI: ``python -m lightgbm_tpu.analysis``.

Exit codes: 0 clean (no unsuppressed findings, all audits pass),
1 findings/audit failures, 2 bad usage or parse errors.

The audit phase runs BOTH engines: the jaxpr audits (traced programs)
and the whole-program auditors (collective order, VMEM/HBM budgets,
recompile surface — see :mod:`auditors`).

Common invocations::

    python -m lightgbm_tpu.analysis                 # full gate
    python -m lightgbm_tpu.analysis --json          # machine report
    python -m lightgbm_tpu.analysis --autofix       # apply safe fixes
    python -m lightgbm_tpu.analysis lightgbm_tpu/ops --rules JG003
    python -m lightgbm_tpu.analysis --write-baseline  # re-grandfather
    python -m lightgbm_tpu.analysis --prune-baseline  # drop stale entries
    python -m lightgbm_tpu.analysis --budgets         # resource tables
    python -m lightgbm_tpu.analysis --list-audits     # audit registry
    python -m lightgbm_tpu.analysis --perf --json     # perf sentinel
    python -m lightgbm_tpu.analysis --perf-advisory   # report, never block
"""
from __future__ import annotations

import argparse
import json
import sys

from . import (auditors, collective_audit, compile_audit,
               concurrency_audit, perf_gate, quant_audit,
               resource_audit)
from .config import load_config
from . import jaxpr_audit
from .jaxpr_audit import run_audits
from .lint import prune_baseline, run_lint, write_baseline
from .rules import all_rules


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="JAX-aware static analysis + jaxpr audit gate")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: [tool.graftlint] "
                        "include roots)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--autofix", action="store_true",
                   help="apply safe textual fixes (unused imports)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline suppression file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write a baseline suppressing all current "
                        "findings, then exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   dest="prune_baseline",
                   help="drop baseline entries no current finding "
                        "matches (stale suppressions), then exit 0")
    p.add_argument("--budgets", action="store_true",
                   help="print the VMEM/HBM budget tables and exit 0")
    p.add_argument("--perf", action="store_true",
                   help="also run the perf-regression sentinel over the "
                        "BENCH_r*/MULTICHIP_r* round series (gates)")
    p.add_argument("--perf-advisory", action="store_true",
                   dest="perf_advisory",
                   help="run the perf sentinel in advisory mode: report "
                        "verdicts, never affect the exit code (the "
                        "pre-commit hook mode)")
    p.add_argument("--no-audit", action="store_true",
                   help="skip the jaxpr/HLO audits")
    p.add_argument("--audit-only", action="store_true",
                   help="run only the jaxpr/HLO audits")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    p.add_argument("--list-audits", action="store_true",
                   dest="list_audits",
                   help="print the audit registry (jaxpr audits + "
                        "whole-program auditors + perf sentinel) and "
                        "exit")
    return p


def _list_audits() -> None:
    """Mirror of --list-rules for the audit side of the gate: every
    jaxpr audit, every registered whole-program auditor, and the
    opt-in perf sentinel, with one-line descriptions."""
    def first_line(doc):
        return (doc or "").strip().splitlines()[0] if doc else ""
    for fn in jaxpr_audit.AUDITS:
        print("jaxpr    %-18s %s" % (fn.__name__.replace("audit_", ""),
                                     first_line(fn.__doc__)))
    for name, mod in sorted(auditors.all_auditors().items()):
        print("auditor  %-18s %s" % (name, first_line(mod.__doc__)))
    print("auditor  %-18s %s" % (
        "perf_sentinel",
        "Perf-regression sentinel over the BENCH_r*/MULTICHIP_r* "
        "round series (opt-in: --perf gates, --perf-advisory "
        "reports)."))


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for rule in all_rules():
            print("%s  %-24s %s" % (rule.id, rule.name, rule.description))
        return 0
    if args.list_audits:
        _list_audits()
        return 0

    config = load_config()
    if args.budgets:
        print(resource_audit.render_tables(
            resource_audit.tables(config=config)))
        return 0
    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)

    if (args.write_baseline or args.prune_baseline) \
            and (args.paths or rule_ids):
        # a filtered report would mark every out-of-scope baseline entry
        # stale (prune drops them) or omit it from the rewrite (write
        # loses it) — both silently destroy grandfathered suppressions
        print("%s requires a full unfiltered scan: drop --rules and "
              "path arguments"
              % ("--write-baseline" if args.write_baseline
                 else "--prune-baseline"), file=sys.stderr)
        return 2

    report = None
    if not args.audit_only:
        report = run_lint(paths=args.paths or None, config=config,
                          rule_ids=rule_ids,
                          use_baseline=not args.no_baseline,
                          autofix=args.autofix)
        if args.write_baseline:
            # full findings list: write_baseline keeps already-baselined
            # entries (they are suppressed, not gone) and skips only
            # inline-suppressed ones — passing unsuppressed here would
            # silently drop every grandfathered entry on refresh
            n = write_baseline(report.findings,
                               config.baseline_path())
            print("wrote %d baseline entries to %s"
                  % (n, config.baseline_path()))
            return 0
        if args.prune_baseline:
            kept, dropped = prune_baseline(report.findings,
                                           config.baseline_path())
            print("pruned %d stale baseline entr%s (%d kept) in %s"
                  % (dropped, "y" if dropped == 1 else "ies", kept,
                     config.baseline_path()))
            return 0

    run_auditors = not (args.no_audit
                        or (args.paths and not args.audit_only))
    # with --json the auditor artifacts also feed the payload below:
    # compute them once and share, instead of re-walking per consumer
    artifacts = (auditors.compute_artifacts(config)
                 if run_auditors and args.as_json else None)
    audits = [] if not run_auditors \
        else run_audits() + auditors.run_all(config, artifacts=artifacts)

    # the perf sentinel is opt-in (--perf gates, --perf-advisory reports
    # without blocking — the pre-commit mode: a clone with no recorded
    # rounds must still be able to commit)
    perf_rep = None
    perf_results = []
    if args.perf or args.perf_advisory:
        perf_rep = perf_gate._resolve_rounds(config)
        perf_results = perf_gate.run(config, artifact=perf_rep)
        audits = audits + perf_results

    bad_audits = [a for a in audits if not a.ok]
    if args.perf_advisory and not args.perf:
        bad_audits = [a for a in bad_audits if a not in perf_results]
    n_unsup = len(report.unsuppressed) if report else 0
    n_parse = len(report.parse_errors) if report else 0
    exit_code = 2 if n_parse else (1 if (n_unsup or bad_audits) else 0)

    if args.as_json:
        payload = {
            "exit_code": exit_code,
            "lint": report.to_dict() if report else None,
            "audits": [a.to_dict() for a in audits],
        }
        if run_auditors:
            # the whole-program auditors' full artifacts: the abstract
            # collective trace, the budget tables, the compile surface
            art = artifacts or {}
            payload["collective_trace"] = \
                collective_audit.extract_repo_trace(
                    config, artifact=art.get("collective_order"))
            payload["resource_tables"] = resource_audit.tables(
                config=config, artifact=art.get("resource_budget"))
            payload["compile_surface"] = compile_audit.compile_surface(
                config, artifact=art.get("compile_surface"))
            # the machine-checkable quantization certificate the
            # item-2/item-3 quantization PRs must ship green against
            payload["quant_certificate"] = \
                quant_audit.certificate_payload(
                    config, artifact=art.get("quant_certify"))
            # the abstract per-root concurrency trace: thread roots,
            # the shared-site/lock-set table, the acquisition-order
            # graph (the threaded host layer's analogue of
            # collective_trace)
            payload["concurrency_trace"] = concurrency_audit.extract_trace(
                config, artifact=art.get("concurrency"))
        if perf_rep is not None:
            payload["perf_tables"] = perf_gate.tables(
                config, artifact=perf_rep)
        print(json.dumps(payload, indent=1))
        return exit_code

    if report:
        shown = report.findings if args.show_suppressed \
            else report.unsuppressed
        for f in shown:
            tag = " [suppressed:%s]" % f.suppression if f.suppressed else ""
            print("%s:%d:%d: %s %s%s"
                  % (f.path, f.line, f.col, f.rule, f.message, tag))
        for path, err in report.parse_errors:
            print("%s: PARSE ERROR: %s" % (path, err))
        if report.autofixed:
            print("autofixed %d import statement(s)" % report.autofixed)
    for a in audits:
        status = "SKIP" if a.skipped else ("ok" if a.ok else "FAIL")
        if (args.perf_advisory and not args.perf
                and a in perf_results and not a.ok):
            status = "ADVISORY-FAIL"
        line = "audit %-24s %s" % (a.name, status)
        if a.detail:
            line += "  (%s)" % a.detail
        print(line)
    if perf_rep is not None:
        print(perf_gate.render_report(perf_rep))
    if report:
        print("graft-lint: %d file(s), %d finding(s) "
              "(%d suppressed), %d audit failure(s)"
              % (report.files_scanned, len(report.findings),
                 len(report.suppressed), len(bad_audits)))
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
