"""Precision-flow auditor: every float narrowing must earn its place.

The repo's worst historical bug class is silent precision drift — the
persist-f32 vs v1-f64 tie-flip took three PRs to pin because an f64
value joined f32 math, shifted a noise-gain split's tie, and two
otherwise-identical runs grew different trees.  The strict jaxpr audit
forbids f64 *inside* the persist-f32 kernels; this auditor covers the
other direction: the **narrowing sites** (f64 -> f32/bf16/f16,
f32 -> bf16/f16) in the traced ``ops/``/``predict/`` programs.  Each
site must be either

* **blessed** — listed in the owning module's ``NARROW_OK`` table (the
  histogram kernel's bf16 hi/lo split is exact by construction and
  blessed in ``ops/pallas_histogram.py``), or
* **proven** — the :mod:`dataflow` interpreter, seeded from the
  module's ``*_input_contract`` annotation, proves a bounded range
  that fits the target dtype AND the narrowed value does not directly
  feed a comparison/argmax.  A *decision-relevant* narrowing can never
  be range-proven: the tie lives inside the discarded mantissa bits —
  that is the tie-flip geometry, and it is this auditor's seeded
  true-positive fixture (``check_fixture({"program": "tie_flip"})``;
  ``LGBTPU_SEED_TIE_FLIP=1`` arms it as a live audit and flips the
  gate to exit 1).

Source-level twin: lint rule JG010 flags ``.astype``/``jnp.asarray``
narrowing in non-allowlisted ``ops/``/``predict/`` files before it is
even traced.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..telemetry import events as telemetry
from . import dataflow
from .config import GraftlintConfig
from .jaxpr_audit import AuditResult, _skip, _toy_ensemble

C_NARROW = "analysis::narrowing_sites"

SEED_TIE_FLIP_ENV = "LGBTPU_SEED_TIE_FLIP"


# ---------------------------------------------------------------------------
# audited programs
#
# Tracing is the expensive half of an audit pass (jax.make_jaxpr plus,
# for predict, a TPUPredictor build), and transfer_audit walks the SAME
# scan_pair/predict programs — so every builder memoizes its traced
# closures once per process and both auditors share them.
# ---------------------------------------------------------------------------

_PROGRAM_MEMO: dict = {}


def _memo(name: str, builder):
    if name not in _PROGRAM_MEMO:
        _PROGRAM_MEMO[name] = builder()
    return _PROGRAM_MEMO[name]


def _hist_prologue():
    """hist_window at both kernel variants: the f32 -> bf16 hi/lo split
    sites, blessed by ops/pallas_histogram.NARROW_OK."""
    from ..ops.pallas_histogram import (NARROW_OK, hist_input_contract,
                                        hist_window)
    out = []
    for w, G, C in ((256, 3, 1024), (64, 5, 512)):
        contract = hist_input_contract(w=w, rows=C)
        closed = jax.make_jaxpr(
            lambda b, g, h, _w=w: hist_window(b, g, h, w=_w))(
                jax.ShapeDtypeStruct((G, C), jnp.int32),
                jax.ShapeDtypeStruct((C,), jnp.float32),
                jax.ShapeDtypeStruct((C,), jnp.float32))
        out.append(("hist_window[w=%d]" % w, closed,
                    {0: contract["bins_t"], 1: contract["grad"],
                     2: contract["hess"]}, NARROW_OK))
    return out


def _scan_pair_program():
    from ..ops.pallas_scan import (NARROW_OK, scan_input_contract,
                                   scan_pair)
    rows = 1 << 20
    contract = scan_input_contract(rows)
    Fp, Wp = 8, 128
    f32 = jnp.float32
    closed = jax.make_jaxpr(scan_pair)(
        jax.ShapeDtypeStruct((2, 8), f32),
        jax.ShapeDtypeStruct((2, Fp, Wp), f32),
        jax.ShapeDtypeStruct((2, Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((8, Fp), f32))
    return [("scan_pair", closed,
             {0: contract["counts"], 1: contract["gb"],
              2: contract["hb"]}, NARROW_OK)]


def _predict_program():
    from ..predict.compile import NARROW_OK
    from ..predict.runtime import TPUPredictor
    pred = TPUPredictor(_toy_ensemble(), dtype="f32", donate=False)
    closed = jax.make_jaxpr(
        lambda x: pred._forward_raw(x, False))(
            jax.ShapeDtypeStruct((64, 3), jnp.float32))
    return [("predict_forward", closed, {0: (-256.0, 256.0)},
             NARROW_OK)]


def _fused_grad_programs():
    """The fused boosting iteration's device gradient kernels (PR 17)
    traced in the persist-f32 payload contract: f32 score/label rows
    in, (grad, hess) out — binary and regression in 'payload' mode,
    multiclass softmax in the K-class snapshot mode. Input ranges
    mirror ops/grow_persist.persist_input_contract (scores bounded by
    the boosting trajectory, labels by their encoding); the strict
    f64-free check on the same traces lives in
    jaxpr_audit.audit_fused_iteration."""
    from types import SimpleNamespace

    import numpy as np

    from ..config import Config
    from ..objectives.base import create_objective

    f32 = jnp.float32
    vec = jax.ShapeDtypeStruct((128,), f32)
    score_rng = (-256.0, 256.0)
    progs = []
    lab = np.asarray([0.0, 1.0] * 8, np.float32)
    meta = SimpleNamespace(label=lab, weight=None)

    obj_b = create_objective("binary", Config(
        {"objective": "binary", "verbosity": -1}))
    obj_b.init(meta, len(lab))
    _mode, fn_b = obj_b.device_gradients()
    progs.append(("fused_grad_binary", jax.make_jaxpr(fn_b)(vec, vec),
                  {0: score_rng, 1: (0.0, 1.0)}, ()))

    obj_r = create_objective("regression", Config(
        {"objective": "regression", "verbosity": -1}))
    obj_r.init(SimpleNamespace(label=np.zeros(16, np.float32),
                               weight=None), 16)
    _mode, fn_r = obj_r.device_gradients()
    progs.append(("fused_grad_regression",
                  jax.make_jaxpr(fn_r)(vec, vec),
                  {0: score_rng, 1: score_rng}, ()))

    obj_m = create_objective("multiclass", Config(
        {"objective": "multiclass", "num_class": 3, "verbosity": -1}))
    obj_m.init(SimpleNamespace(
        label=(np.arange(16) % 3).astype(np.float32), weight=None), 16)
    _mode, fn_m = obj_m.device_gradients()
    progs.append(("fused_grad_multiclass",
                  jax.make_jaxpr(lambda s, l: fn_m(s, l, 1))(
                      jax.ShapeDtypeStruct((3, 128), f32), vec),
                  {0: score_rng, 1: (0.0, 2.0)}, ()))
    return progs


def _tie_flip_program():
    """The seeded true-positive: split gains computed in f64, narrowed
    to f32 BEFORE the argmax — the exact tie-flip geometry.  The
    contract bounds every input, yet the site must still fail: the
    narrowed value feeds the decision, and one child's ``H + lambda``
    denominator straddles zero under the abstract ranges, so neither
    blessing nor proof exists."""
    n = 4096.0

    def gains(gl, hl, gp, hp):
        lam = jnp.float64(1.0)
        gr = gp - gl
        hr = hp - hl
        gain = (gl * gl) / (hl + lam) + (gr * gr) / (hr + lam) \
            - (gp * gp) / (hp + lam)
        g32 = gain.astype(jnp.float32)      # narrowed before the decision
        return jnp.max(g32), jnp.argmax(g32)

    f64 = jnp.float64
    shape = jax.ShapeDtypeStruct((256,), f64)
    closed = jax.make_jaxpr(gains)(shape, shape, shape, shape)
    return [("tie_flip", closed,
             {0: (-n, n), 1: (0.0, n / 4), 2: (-n, n),
              3: (0.0, n / 4)}, ())]


def _bounded_narrow_program():
    """Clean twin: an f64 -> f32 narrowing whose contract-proven range
    fits f32 and that feeds plain arithmetic, not a comparison."""
    def scale(x):
        y = (x * jnp.float64(0.5)).astype(jnp.float32)
        return y + jnp.float32(1.0)

    closed = jax.make_jaxpr(scale)(
        jax.ShapeDtypeStruct((128,), jnp.float64))
    return [("bounded_narrow", closed, {0: (-1000.0, 1000.0)}, ())]


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def _violations(name: str, closed, ranges, blessed
                ) -> Tuple[List[str], int]:
    """(violation strings, narrowing-site count) for one program."""
    rep = dataflow.interpret(closed, in_ranges=ranges)
    bless = {tuple(p) for p in blessed}
    bad = []
    for site in rep.narrowings:
        if (site.src, site.dst) in bless:
            continue
        if site.weak_src and not site.decision_relevant:
            # a weak-typed scalar round-trip (python float promoted
            # under x64, narrowed straight back) is the JG003 source
            # class, policed at the AST layer — unless it decides
            continue
        if site.decision_relevant:
            bad.append("%s: %s — decision-relevant narrowing must be "
                       "blessed (the tie-flip class)"
                       % (name, site.describe()))
        elif not site.fits:
            bad.append("%s: %s — range not proven to fit %s"
                       % (name, site.describe(), site.dst))
    return bad, len(rep.narrowings)


def _programs(include_seeded: bool) -> List[Tuple]:
    from ..ops.pallas_compat import HAS_PALLAS
    progs: List[Tuple] = []
    if HAS_PALLAS:
        progs += _memo("hist_prologue", _hist_prologue)
        progs += _memo("scan_pair", _scan_pair_program)
    progs += _memo("predict", _predict_program)
    progs += _memo("fused_grads", _fused_grad_programs)
    if include_seeded:
        progs += _tie_flip_program()
    return progs


def compute_artifact(config: Optional[GraftlintConfig] = None) -> dict:
    """One engine pass over the audited programs; shared by run() and
    the --json payload builder."""
    include_seeded = os.environ.get(SEED_TIE_FLIP_ENV, "") \
        not in ("", "0")
    from ..ops.pallas_compat import HAS_PALLAS
    violations: List[str] = []
    n_sites = 0
    names = []
    for name, closed, ranges, blessed in _programs(include_seeded):
        bad, n = _violations(name, closed, ranges, blessed)
        violations += bad
        n_sites += n
        names.append(name)
    return {"programs": names, "violations": violations,
            "narrowing_sites": n_sites, "pallas": HAS_PALLAS,
            "seeded": include_seeded}


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    name = "precision_flow"
    try:
        art = artifact if isinstance(artifact, dict) \
            else compute_artifact(config)
    except Exception as e:      # pragma: no cover - defensive
        return [AuditResult(name=name, ok=False,
                            detail="auditor raised: %r" % e)]
    if not art["programs"]:
        return [_skip(name, "pallas unavailable")]
    telemetry.count(C_NARROW, art["narrowing_sites"],
                    category="analysis")
    ok_detail = ("%d narrowing site(s) across %d program(s), all "
                 "blessed or range-proven"
                 % (art["narrowing_sites"], len(art["programs"])))
    return [AuditResult(
        name=name, ok=not art["violations"],
        detail="; ".join(art["violations"][:3]) if art["violations"]
        else ok_detail)]


def check_fixture(payload: dict) -> List[str]:
    """Uniform fixture hook: {"program": "tie_flip" | "bounded_narrow"}
    — the seeded tie-flip geometry must be flagged, the range-proven
    narrowing must not."""
    program = payload["program"]
    builders: dict = {"tie_flip": _tie_flip_program,
                      "bounded_narrow": _bounded_narrow_program}
    out: List[str] = []
    for name, closed, ranges, blessed in builders[program]():
        bad, _ = _violations(name, closed, ranges, blessed)
        out += bad
    return out
