"""JG009 — host-side DCN collectives outside the resilience retry guard.

Every host collective (``multihost_utils.process_allgather`` and
friends) is a synchronous rendezvous: a gone peer turns an unguarded
call into an infinite hang, which is why ``resilience/retry.py`` exists
— its ``guard`` runs the collective under a deadline with bounded
retries and raises a clean ``LightGBMError`` a scheduler can restart.
The contract (PR 5) is that EVERY DCN collective call site in the
distributed modules goes through it::

    resilience_retry.guard("allgather:row_counts",
                           multihost_utils.process_allgather, arr)

This rule flags a *direct call* to a known collective inside the
configured ``collective_paths`` (parallel/, resilience/ by default).
Passing the collective as guard's ``fn`` argument is not a call and
stays silent; so does a call made inside a lambda/closure handed to
``guard``. The whole-program twin of this rule is
``analysis/collective_audit.py``'s ``collective_guarded`` audit — the
lint form exists so a new unguarded site fails with a file:line finding
(and a fixture) instead of an audit-level summary.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

# final-attribute names of the host-side DCN collectives
_COLLECTIVES = ("process_allgather", "process_allgather_tree",
                "broadcast_one_to_all", "sync_global_devices")


@register
class UnguardedCollective:
    id = "JG009"
    name = "unguarded-collective"
    description = ("direct DCN collective call bypassing the "
                   "resilience retry guard hangs forever on a gone peer")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not any(frag in ctx.relpath
                   for frag in ctx.config.collective_paths):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.call_target(node)
            if target is None \
                    or target.split(".")[-1] not in _COLLECTIVES:
                continue
            if self._inside_guard(ctx, node):
                continue
            out.append(ctx.finding(
                self.id, node,
                "`%s` called directly; wrap it with "
                "resilience_retry.guard(name, fn, ...) so a gone peer "
                "raises a bounded-retry error instead of hanging"
                % target.split(".")[-1]))
        return out

    def _inside_guard(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """True when the call happens inside a guard(...) invocation —
        a lambda or nested closure handed to the guard still runs under
        its deadline thread."""
        cur = ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.Call):
                t = ctx.call_target(cur)
                if t is not None and t.split(".")[-1] == "guard":
                    return True
            cur = ctx.parent.get(cur)
        return False
