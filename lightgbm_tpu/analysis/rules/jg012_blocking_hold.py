"""JG012 — lock held across a blocking operation.

A critical section should be a few loads and stores; holding a lock
across ``time.sleep``, a thread ``join``, a future ``.result()``, a
device sync (``block_until_ready`` / ``finalize_padded``), or a
retry-guarded DCN collective turns every contending thread into a
convoy behind that one slow operation — and a ``join`` on a thread that
itself needs the lock is a deadlock, not a slowdown. The sanctioned
exception is ``Condition.wait`` on the very lock being held (wait
releases it; that is the condition-variable protocol)::

    with self._cond:
        self._cond.wait(timeout=0.01)       # fine: wait releases _cond
        fut.result()                        # JG012: convoy / deadlock

One call level is tracked: invoking a same-module helper whose body
blocks, with a lock in hand, is flagged too. Shares the cached
per-module analysis with JG011; scoped to ``concurrency_paths``. The
whole-program twin (lock-order cycles included) is the ``concurrency``
auditor.
"""
from __future__ import annotations

from typing import List

from .. import concurrency_audit
from ..core import Finding, ModuleContext
from . import register
from .jg011_unguarded_shared import _scoped, _to_finding


@register
class BlockingHold:
    id = "JG012"
    name = "lock-held-across-blocking"
    description = ("lock held across a blocking operation (sleep/join/"
                   "result/device sync/collective) convoys or deadlocks")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not _scoped(ctx):
            return []
        return [_to_finding(ctx, self.id, f)
                for f in concurrency_audit.module_findings(ctx)
                if f.rule == "JG012"]
