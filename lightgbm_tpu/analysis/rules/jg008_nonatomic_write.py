"""JG008 — non-atomic file write on a durability-critical path.

A checkpoint (or any resume-critical state file) written with a plain
``open(path, "w")`` can be torn by a preemption mid-write: the next run
then resumes from garbage, or — worse — from a file whose length is right
but whose tail is stale. The resilience subsystem's contract is
tmp + flush + fsync + ``os.replace`` (``resilience/checkpoint.py``
``atomic_write_bytes``), which leaves either the old file or the complete
new one.

Within the configured ``atomic_write_paths`` (default:
``lightgbm_tpu/resilience/``) this rule flags every write-mode ``open``
call unless BOTH hold:

  * the file argument is visibly a temp target (an identifier, attribute
    or string containing ``tmp``), and
  * the module publishes it atomically somewhere (calls ``os.replace`` /
    ``os.rename``).

Reads are never flagged. Intentional corruption helpers (fault
injection) carry an inline ``# graftlint: disable=JG008``.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_WRITE_CHARS = ("w", "a", "x", "+")


_OS_WRITE_FLAGS = ("O_WRONLY", "O_RDWR", "O_APPEND", "O_CREAT", "O_TRUNC")


def _mode_of(call: ast.Call):
    """The mode string of an open() call, or None when undecidable."""
    if len(call.args) >= 2:
        node = call.args[1]
    else:
        node = next((kw.value for kw in call.keywords
                     if kw.arg == "mode"), None)
    if node is None:
        return "r"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    # os.open takes int flags: writeable iff an O_* write flag appears
    names = {sub.attr for sub in ast.walk(node)
             if isinstance(sub, ast.Attribute)}
    names |= {sub.id for sub in ast.walk(node) if isinstance(sub, ast.Name)}
    if any(n.startswith("O_") for n in names):
        return "w" if names & set(_OS_WRITE_FLAGS) else "r"
    return None


def _looks_tmp(node: ast.AST) -> bool:
    """True when the file-argument expression visibly names a temp target."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and "tmp" in sub.value.lower():
            return True
    return False


def _is_open(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        return True
    return (isinstance(f, ast.Attribute) and f.attr == "open"
            and isinstance(f.value, ast.Name) and f.value.id in ("io", "os"))


def _module_renames(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in ("replace", "rename") and isinstance(
                    node.func.value, ast.Name) and node.func.value.id == "os":
                return True
    return False


@register
class NonAtomicWrite:
    id = "JG008"
    name = "non-atomic-write"
    description = ("open-for-write without tmp + os.replace on a "
                   "durability-critical path (torn checkpoint on kill)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        cfg_paths = getattr(ctx.config, "atomic_write_paths", ())
        rp = ctx.relpath
        if not any(rp.startswith(frag) or frag in rp for frag in cfg_paths):
            return []
        out: List[Finding] = []
        has_rename = _module_renames(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_open(node)
                    and node.args):
                continue
            mode = _mode_of(node)
            if mode is not None and not any(c in mode
                                            for c in _WRITE_CHARS):
                continue   # read-only open
            if _looks_tmp(node.args[0]) and has_rename:
                continue   # tmp target + module publishes via os.replace
            out.append(ctx.finding(
                self.id, node,
                "write files atomically: open a '*.tmp' sibling, flush + "
                "fsync, then os.replace onto the final name "
                "(resilience.checkpoint.atomic_write_bytes)"))
        return out
