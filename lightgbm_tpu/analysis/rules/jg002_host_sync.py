"""JG002 — host syncs inside hot-path loops.

The growers and the serving path are async device pipelines: the host
queues work and only blocks at deliberate `device_wait` points. A
``.item()`` / ``float(dev_array)`` / ``np.asarray(dev_array)`` inside a
per-tree/per-split/per-batch host loop silently serializes the pipeline
— every iteration round-trips to the device, and the profiler shows the
cost as idle host time rather than a named span.

Scope: files under the configured ``hot_paths`` (ops/, predict/,
parallel/ by default), ``for``/``while`` bodies only, *host* code only —
loops inside jit scopes are traced, where these calls either fail loudly
or run once at trace time, so they are excluded rather than double-
reported. Deliberate end-of-pipeline syncs stay allowed via inline
``# graftlint: disable=JG002`` or the baseline file.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_NP_SYNCS = ("numpy.asarray", "numpy.array", "numpy.ascontiguousarray")
_BUILTIN_SYNCS = ("float",)


@register
class HostSyncInHotLoop:
    id = "JG002"
    name = "host-sync-in-hot-loop"
    description = ("`.item()` / `float()` / `np.asarray()` inside a "
                   "hot-path host loop forces a device sync per iteration")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not ctx.config.is_hot_path(ctx.relpath):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not ctx.in_host_loop(node) or ctx.in_jit_scope(node):
                continue
            msg = self._sync_kind(ctx, node)
            if msg:
                out.append(ctx.finding(
                    self.id, node,
                    msg + " inside a hot-path loop forces a per-iteration "
                    "device sync; hoist it or batch the transfer"))
        return out

    def _sync_kind(self, ctx: ModuleContext, node: ast.Call) -> str:
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args and not node.keywords:
            return "`.item()`"
        target = ctx.call_target(node)
        if target in _NP_SYNCS:
            return "`np.%s()`" % target.split(".", 1)[1]
        if target in _BUILTIN_SYNCS and target not in ctx.aliases:
            # float(x)/int(x) on non-literals; literal casts are static
            if len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                return "`%s()`" % target
        return ""
