"""JG011 — unguarded mutation of state shared between thread roots.

The threaded host layer (serving loop, registry hot-swap, retry
watchdog, telemetry registries) keeps its shared mutable state behind
locks; this rule is the file:line lint form of the whole-program
``concurrency`` auditor's lock-discipline analysis. A write to a module
global or a lock-owning class's instance attribute that is reachable
from two thread roots (or sits on a lock-owning — hence declared
multi-threaded — surface) must either hold the object's lock, be
GIL-atomic (single-reference publish, ``deque.append``-class container
ops), happen in ``__init__`` (pre-publication), or carry an explicit
``# guarded-by: <lock|root|GIL>`` annotation naming the documented
invariant. Anything else is a data race in waiting::

    with self._cond:
        self._depth += 1          # fine: lock held
    self._errors += len(group)    # JG011: read-modify-write, no lock

The rule and its JG012 sibling share one cached per-module analysis
(:func:`~lightgbm_tpu.analysis.concurrency_audit.module_findings`), so
the pair costs a single AST pass. Scoped to ``concurrency_paths``.
"""
from __future__ import annotations

from typing import List

from .. import concurrency_audit
from ..core import Finding, ModuleContext
from . import register


def _to_finding(ctx: ModuleContext, rule_id: str, f) -> Finding:
    snippet = (ctx.lines[f.line - 1].strip()
               if 0 < f.line <= len(ctx.lines) else "")
    return Finding(rule=rule_id, path=f.path, line=f.line, col=0,
                   message=f.message, snippet=snippet)


def _scoped(ctx: ModuleContext) -> bool:
    return any(frag in ctx.relpath
               for frag in ctx.config.concurrency_paths)


@register
class UnguardedShared:
    id = "JG011"
    name = "unguarded-shared-mutation"
    description = ("mutation of thread-shared state without its lock, "
                   "a GIL-atomic blessing, or a # guarded-by: note")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not _scoped(ctx):
            return []
        return [_to_finding(ctx, self.id, f)
                for f in concurrency_audit.module_findings(ctx)
                if f.rule == "JG011"]
