"""Graft-lint rule registry.

A rule is a class with ``id``, ``name``, ``description`` and a
``check(ctx) -> list[Finding]`` method over a
:class:`~lightgbm_tpu.analysis.core.ModuleContext`. Register with the
:func:`register` decorator; the engine iterates :func:`all_rules` in id
order. To add a rule: drop a module in this package, define the class,
decorate it, and import it below — then give it a positive + negative
fixture in tests/test_analysis.py (the fixture test parametrizes over
the registry, so a rule without fixtures fails CI by construction).
"""
from __future__ import annotations

from typing import Dict, List, Type

_REGISTRY: Dict[str, object] = {}


def register(cls):
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError("duplicate rule id %s" % inst.id)
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> List[object]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str):
    return _REGISTRY[rule_id]


from . import jg001_traced_control  # noqa: E402,F401
from . import jg002_host_sync  # noqa: E402,F401
from . import jg003_weak_literals  # noqa: E402,F401
from . import jg004_jit_in_loop  # noqa: E402,F401
from . import jg005_nondeterminism  # noqa: E402,F401
from . import jg006_raw_pallas  # noqa: E402,F401
from . import jg007_unused_imports  # noqa: E402,F401
from . import jg008_nonatomic_write  # noqa: E402,F401
from . import jg009_unguarded_collective  # noqa: E402,F401
from . import jg010_unblessed_narrowing  # noqa: E402,F401
from . import jg011_unguarded_shared  # noqa: E402,F401
from . import jg012_blocking_hold  # noqa: E402,F401
