"""JG007 — unused imports (the autofix-driven dead-code sweep).

Unused imports are not just noise: in this codebase an import can pull
in jax machinery with real side effects (device init, x64 config), and
stale imports are where dead subsystems hide after a refactor. The rule
is deliberately conservative so its autofix is safe to run blind:

* usage = the bound name appearing as a word ANYWHERE else in the file
  (code, annotations, docstrings, ``__all__`` strings) — false "used"
  beats false "unused";
* skipped entirely: ``__init__.py`` (re-export surface), ``__future__``
  imports, star imports, ``# noqa`` lines, and imports inside
  ``try:`` blocks (version/feature probing idiom, e.g. pallas_compat).

The fix rewrites the import statement without the dead names, or
removes it outright; the engine applies fixes bottom-up so line numbers
stay valid.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..core import Finding, ModuleContext
from . import register


def _binding(alias: ast.alias, is_from: bool) -> str:
    if alias.asname:
        return alias.asname
    return alias.name if is_from else alias.name.split(".")[0]


def _rebuild(node, kept: List[ast.alias], indent: str) -> str:
    def fmt(a: ast.alias) -> str:
        return a.name + (" as " + a.asname if a.asname else "")
    names = [fmt(a) for a in kept]
    if not isinstance(node, ast.ImportFrom):
        # plain `import a, b` has no parenthesized form; a long line is
        # valid Python, which beats a SyntaxError
        return indent + "import " + ", ".join(names)
    mod = "." * node.level + (node.module or "")
    stmt = "from %s import %s" % (mod, ", ".join(names))
    if len(indent + stmt) <= 79:
        return indent + stmt
    # wrap: from m import (a, b,\n<align>c)
    head = indent + "from %s import (" % mod
    cont = " " * len(head)
    lines, cur = [], head
    for i, nm in enumerate(names):
        piece = nm + ("," if i < len(names) - 1 else ")")
        if cur != head and cur != cont and len(cur) + len(piece) + 1 > 79:
            lines.append(cur)
            cur = cont
        cur += piece if cur in (head, cont) else " " + piece
    lines.append(cur)
    return "\n".join(lines)


@register
class UnusedImports:
    id = "JG007"
    name = "unused-import"
    description = "import bound to a name the module never uses"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.relpath.endswith("__init__.py"):
            return []
        out: List[Finding] = []
        src = ctx.source
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "__future__":
                continue
            if any(a.name == "*" for a in node.names):
                continue
            if self._in_try(ctx, node) or self._has_noqa(ctx, node):
                continue
            is_from = isinstance(node, ast.ImportFrom)
            seg = ast.get_source_segment(src, node) or ""
            unused, kept = [], []
            for a in node.names:
                name = _binding(a, is_from)
                total = len(re.findall(r"\b%s\b" % re.escape(name), src))
                inside = len(re.findall(r"\b%s\b" % re.escape(name), seg))
                (unused if total <= inside else kept).append(a)
            if not unused:
                continue
            indent = ctx.lines[node.lineno - 1][
                :len(ctx.lines[node.lineno - 1])
                - len(ctx.lines[node.lineno - 1].lstrip())]
            new_text: Optional[str] = (
                _rebuild(node, kept, indent) if kept else None)
            fix = ("replace_span", (node.lineno, node.end_lineno, new_text))
            for i, a in enumerate(unused):
                out.append(ctx.finding(
                    self.id, node,
                    "imported name `%s` is never used"
                    % _binding(a, is_from),
                    fix=fix if i == 0 else None))
        return out

    def _in_try(self, ctx: ModuleContext, node: ast.AST) -> bool:
        cur = ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.Try):
                return True
            cur = ctx.parent.get(cur)
        return False

    def _has_noqa(self, ctx: ModuleContext, node: ast.AST) -> bool:
        for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
            if 0 < ln <= len(ctx.lines) and "# noqa" in ctx.lines[ln - 1]:
                return True
        return False
