"""JG007 — unused imports (the autofix-driven dead-code sweep).

Unused imports are not just noise: in this codebase an import can pull
in jax machinery with real side effects (device init, x64 config), and
stale imports are where dead subsystems hide after a refactor. The rule
is deliberately conservative so its autofix is safe to run blind:

* usage = the bound name appearing as a word anywhere OUTSIDE the
  removable import statements themselves (code, annotations, docstrings,
  ``__all__`` strings) — false "used" beats false "unused". Other
  *removable* import segments are blanked before counting: a name whose
  only other appearance is inside an import this same rule may delete
  (``import os`` next to ``from os import path``) must count as unused
  NOW, or the first ``--autofix`` pass unmasks it and the second pass
  edits the file again — the idempotency bug the round-trip test pins;
* skipped entirely: ``__init__.py`` (re-export surface), ``__future__``
  imports, star imports, ``# noqa`` lines, imports inside ``try:``
  blocks (version/feature probing idiom, e.g. pallas_compat), and
  imports sharing a source line with anything else (``import os; x=1``,
  trailing comments) — the counting and the fix are both line-grained.

The fix rewrites the import statement without the dead names, or
removes it outright; the engine applies fixes bottom-up so line numbers
stay valid.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..core import Finding, ModuleContext
from . import register


def _binding(alias: ast.alias, is_from: bool) -> str:
    if alias.asname:
        return alias.asname
    return alias.name if is_from else alias.name.split(".")[0]


def _rebuild(node, kept: List[ast.alias], indent: str) -> str:
    def fmt(a: ast.alias) -> str:
        return a.name + (" as " + a.asname if a.asname else "")
    names = [fmt(a) for a in kept]
    if not isinstance(node, ast.ImportFrom):
        # plain `import a, b` has no parenthesized form; a long line is
        # valid Python, which beats a SyntaxError
        return indent + "import " + ", ".join(names)
    mod = "." * node.level + (node.module or "")
    stmt = "from %s import %s" % (mod, ", ".join(names))
    if len(indent + stmt) <= 79:
        return indent + stmt
    # wrap: from m import (a, b,\n<align>c)
    head = indent + "from %s import (" % mod
    cont = " " * len(head)
    lines, cur = [], head
    for i, nm in enumerate(names):
        piece = nm + ("," if i < len(names) - 1 else ")")
        if cur != head and cur != cont and len(cur) + len(piece) + 1 > 79:
            lines.append(cur)
            cur = cont
        cur += piece if cur in (head, cont) else " " + piece
    lines.append(cur)
    return "\n".join(lines)


@register
class UnusedImports:
    id = "JG007"
    name = "unused-import"
    description = "import bound to a name the module never uses"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.relpath.endswith("__init__.py"):
            return []
        out: List[Finding] = []
        removable = [node for node in ast.walk(ctx.tree)
                     if self._removable(ctx, node)]
        usage_src = self._blank_segments(ctx, removable)
        for node in removable:
            is_from = isinstance(node, ast.ImportFrom)
            unused, kept = [], []
            for a in node.names:
                name = _binding(a, is_from)
                used = re.search(r"\b%s\b" % re.escape(name), usage_src)
                (kept if used else unused).append(a)
            if not unused:
                continue
            indent = ctx.lines[node.lineno - 1][
                :len(ctx.lines[node.lineno - 1])
                - len(ctx.lines[node.lineno - 1].lstrip())]
            new_text: Optional[str] = (
                _rebuild(node, kept, indent) if kept else None)
            fix = ("replace_span", (node.lineno, node.end_lineno, new_text))
            for i, a in enumerate(unused):
                out.append(ctx.finding(
                    self.id, node,
                    "imported name `%s` is never used"
                    % _binding(a, is_from),
                    fix=fix if i == 0 else None))
        return out

    def _removable(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """Import statements this rule is allowed to rewrite/delete."""
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            return False
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            return False
        if any(a.name == "*" for a in node.names):
            return False
        if not self._owns_its_lines(ctx, node):
            return False
        return not (self._in_try(ctx, node) or self._has_noqa(ctx, node))

    def _owns_its_lines(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """True when nothing else shares the import's source lines.

        Both the usage count (whole-line blanking) and the fix
        (whole-line replace_span) operate on full lines, so an import
        sharing a line with other code (``import os; x = os.path``, or
        a trailing comment) must stay untouched — deleting the line
        would take the neighbour with it."""
        seg = ast.get_source_segment(ctx.source, node)
        if seg is None:
            return False
        seg_lines = seg.splitlines()
        first = ctx.lines[node.lineno - 1].strip()
        last = ctx.lines[(node.end_lineno or node.lineno) - 1].strip()
        return (first == seg_lines[0].strip()
                and last == seg_lines[-1].strip())

    def _blank_segments(self, ctx: ModuleContext, nodes) -> str:
        """Source with every removable import's lines blanked — the text
        usage is counted against. Blanking ALL of them at once (not just
        the statement under test) keeps the fix idempotent: a name whose
        only other mention is inside another deletable import would
        otherwise look used until that import is deleted, and the NEXT
        autofix pass would touch the file again."""
        lines = list(ctx.lines)
        for node in nodes:
            for ln in range(node.lineno, (node.end_lineno or node.lineno)
                            + 1):
                if 0 < ln <= len(lines):
                    lines[ln - 1] = ""
        return "\n".join(lines)

    def _in_try(self, ctx: ModuleContext, node: ast.AST) -> bool:
        cur = ctx.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.Try):
                return True
            cur = ctx.parent.get(cur)
        return False

    def _has_noqa(self, ctx: ModuleContext, node: ast.AST) -> bool:
        for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
            if 0 < ln <= len(ctx.lines) and "# noqa" in ctx.lines[ln - 1]:
                return True
        return False
