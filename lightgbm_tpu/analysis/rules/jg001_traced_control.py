"""JG001 — Python control flow on traced values inside jitted code.

``if jnp.any(x):`` inside a jit/kernel scope either raises a
ConcretizationError at trace time or, worse, silently bakes one branch
into the compiled program when the value happens to be concrete during
tracing (a constant-folded input). Both are trace bugs: data-dependent
branching belongs in ``jax.lax.cond`` / ``jnp.where`` / ``pl.when``.

The static approximation: within a jit scope (jit-decorated function,
kernel-pattern function, or anything nested in one), flag ``if``/
``while`` whose test expression calls into ``jax.*`` / ``jax.numpy.*``
(``jax.debug`` excluded). Tests on plain Python names — static config
flags, geometry ints — stay silent, which is what keeps the repo's
jitted growers (full of ``if use_radix:``-style static dispatch) clean.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_TRACED_ROOTS = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.scipy.")
_EXEMPT = ("jax.debug.", "jax.tree_util.", "jax.core.")


@register
class TracedControlFlow:
    id = "JG001"
    name = "traced-control-flow"
    description = ("Python if/while on a traced (jax/jnp) value inside a "
                   "jitted call graph; use lax.cond/jnp.where/pl.when")

    def _test_is_traced(self, ctx: ModuleContext, test: ast.AST) -> bool:
        for node in ast.walk(test):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.call_target(node)
            if target is None:
                continue
            t = target + "."
            if t.startswith(_EXEMPT):
                continue
            if t.startswith(_TRACED_ROOTS) or t.startswith("jax."):
                return True
        return False

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if not ctx.in_jit_scope(node):
                continue
            if self._test_is_traced(ctx, node.test):
                kind = "while" if isinstance(node, ast.While) else "if"
                out.append(ctx.finding(
                    self.id, node,
                    "Python `%s` on a traced value inside a jitted scope; "
                    "use jax.lax.cond / jnp.where / pl.when" % kind))
        return out
