"""JG004 — jit/pallas program construction inside per-tree/per-split loops.

``jax.jit(f)`` (and a raw ``pl.pallas_call`` construction) builds a NEW
callable with its own compile cache entry; doing it inside a host loop
means every iteration traces and compiles from scratch — the classic
recompile storm that turns a 50ms training iteration into seconds. The
serving path's whole bucket-ladder design exists to bound compile
counts; this rule keeps the construction sites out of loops so the
ladder bound is the only compile multiplier.

Builders that close over loop state legitimately (``make_split_pass``
called once per payload geometry) are fine because the *call to jit*
happens once inside the builder, not in the loop — but calling the
BUILDER itself per loop iteration is the same storm wearing a trench
coat, so the known kernel builders (``make_split_pass``,
``make_level_pass``, …) are flagged in host loops too. The
level-parallel grower (PR 7) depends on this: its level/split kernels
are built once in ``make_persist_grower`` and invoked from inside the
traced level loop; a builder call drifting into the host per-level or
per-batch loop would silently reintroduce the ~per-split compile cost
the level program exists to eliminate.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_COMPILE_CALLS = ("jax.jit", "jax.pmap", "jit")

# kernel BUILDERS: each constructs a jit/pallas_call inside; calling one
# per loop iteration is a recompile storm one frame removed
_KERNEL_BUILDERS = (
    "make_split_pass", "make_level_pass", "make_level_seg_hist",
    "make_seg_hist", "make_root_hist", "make_persist_grower",
)


@register
class JitInLoop:
    id = "JG004"
    name = "jit-in-loop"
    description = ("jax.jit / pallas_call construction inside a host "
                   "loop recompiles every iteration")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not ctx.in_host_loop(node):
                continue
            target = ctx.call_target(node)
            if target in _COMPILE_CALLS:
                out.append(ctx.finding(
                    self.id, node,
                    "`%s(...)` inside a loop builds a fresh compiled "
                    "callable per iteration; hoist the jit out of the "
                    "loop" % target))
            elif target is not None and target.endswith(".pallas_call"):
                out.append(ctx.finding(
                    self.id, node,
                    "`pallas_call` construction inside a loop re-traces "
                    "the kernel per iteration; build it once and reuse"))
            elif target is not None \
                    and target.split(".")[-1] in _KERNEL_BUILDERS:
                out.append(ctx.finding(
                    self.id, node,
                    "`%s(...)` inside a loop rebuilds (and so "
                    "recompiles) its kernel per iteration; build it "
                    "once per payload geometry and reuse"
                    % target.split(".")[-1]))
            elif target in ("functools.partial", "partial") and node.args \
                    and ctx.dotted(node.args[0]) in _COMPILE_CALLS:
                out.append(ctx.finding(
                    self.id, node,
                    "partial(jax.jit, ...) inside a loop builds a fresh "
                    "compiled callable per iteration"))
        return out
