"""JG010 — unblessed narrowing casts in the numeric hot paths.

The precision-flow auditor (analysis/precision_audit.py) verifies
narrowings in the TRACED programs; this rule is its source-level twin:
a ``.astype(...)`` or ``jnp.asarray(..., dtype=...)`` narrowing to
f32/bf16/f16/int8/int16 inside ``ops/`` or ``predict/`` is exactly
where the next tie-flip gets planted, so new narrowing sites may only
appear in files listed in the ``[tool.graftlint] narrow-ok-paths``
allowlist — the modules whose narrowings are blessed (and certified)
through their ``NARROW_OK`` tables and input contracts.  Everything
else must either stay wide, move into an allowlisted module, or make
the deliberate-and-justified case inline
(``# graftlint: disable=JG010``).

Casts to f64, casts to a dynamic dtype (``x.astype(y.dtype)``), and
code outside ``ops/``/``predict/`` are not flagged.  The rule is
SOURCE-BLIND: any static cast TO one of the narrow dtypes fires, even
when the value being cast is already that narrow or narrower (an
upcast like ``leaf_f16.astype(jnp.float32)``) — the AST cannot see the
operand's dtype, and a hot-path file full of casts to the narrow
dtypes belongs in the allowlist (with its ``NARROW_OK`` blessing)
anyway; a genuinely-widening one-off earns its inline disable.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, ModuleContext
from . import register

# static narrow targets (f64 -> these, or f32 -> the 16-bit ones, or
# float -> the quantized int payload dtypes)
_NARROW = {"float32", "bfloat16", "float16", "int8", "int16"}
_SCOPE = ("lightgbm_tpu/ops/", "lightgbm_tpu/predict/")
_FROM_VALUE = {"asarray", "array"}
_JNP = "jax.numpy."


def _narrow_target(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    """The narrow dtype a static cast argument names, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _NARROW else None
    dotted = ctx.dotted(node)
    if dotted is None:
        return None
    leaf = dotted.rsplit(".", 1)[-1]
    return leaf if leaf in _NARROW else None


@register
class UnblessedNarrowing:
    id = "JG010"
    name = "unblessed-narrowing"
    description = ("`.astype`/`jnp.asarray` narrowing to f32/bf16/f16/"
                   "int8/int16 in ops//predict/ outside the "
                   "[tool.graftlint] narrow-ok-paths allowlist (the "
                   "tie-flip planting ground)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        rp = ctx.relpath
        if not any(frag in rp for frag in _SCOPE):
            return []
        if any(frag in rp for frag in ctx.config.narrow_ok_paths):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dt = self._cast_target(ctx, node)
            if dt:
                out.append(ctx.finding(
                    self.id, node,
                    "narrowing cast to %s outside the narrow-ok-paths "
                    "allowlist; keep the value wide, move the site "
                    "into an allowlisted module with a NARROW_OK "
                    "blessing, or justify it inline" % dt))
        return out

    def _cast_target(self, ctx: ModuleContext,
                     node: ast.Call) -> Optional[str]:
        # x.astype(<narrow>) / x.astype(dtype=<narrow>)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype":
            if node.args:
                return _narrow_target(ctx, node.args[0])
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _narrow_target(ctx, kw.value)
            return None
        # jnp.asarray(x, <narrow>) / jnp.asarray(x, dtype=<narrow>)
        target = ctx.call_target(node)
        if target is None or not target.startswith(_JNP):
            return None
        if target[len(_JNP):] not in _FROM_VALUE:
            return None
        for kw in node.keywords:
            if kw.arg == "dtype":
                return _narrow_target(ctx, kw.value)
        if len(node.args) >= 2:
            return _narrow_target(ctx, node.args[1])
        return None
