"""JG006 — pallas imported around the compat shim.

``ops/pallas_compat.py`` is the single import point for the Pallas TPU
API: it papers over the ``TPUCompilerParams``/``CompilerParams`` rename,
provides the ``enable_x64`` shim, and — critically — degrades to
``HAS_PALLAS = False`` so every caller takes its guarded XLA fallback on
builds where pallas cannot construct kernels. A module that imports
``jax.experimental.pallas`` directly bypasses all three: it crashes on
0.4.x/exotic builds instead of falling back, and silently skips the
version shims. Only the modules listed in ``pallas_compat_allow``
(the shim itself) may touch the raw import.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_RAW = "jax.experimental.pallas"


@register
class RawPallasImport:
    id = "JG006"
    name = "raw-pallas-import"
    description = ("direct jax.experimental.pallas import bypasses "
                   "ops/pallas_compat.py (version shims + XLA fallback)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        allowed = {p.replace("\\", "/")
                   for p in ctx.config.pallas_compat_allow}
        if ctx.relpath in allowed:
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            hit = False
            if isinstance(node, ast.Import):
                hit = any(a.name == _RAW or a.name.startswith(_RAW + ".")
                          for a in node.names)
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                mod = node.module or ""
                hit = mod == _RAW or mod.startswith(_RAW + ".") or (
                    mod == "jax.experimental"
                    and any(a.name == "pallas" for a in node.names))
            if hit:
                out.append(ctx.finding(
                    self.id, node,
                    "import pallas via ops/pallas_compat.py (pl, pltpu, "
                    "TPUCompilerParams, HAS_PALLAS), not directly"))
        return out
