"""JG005 — unseeded RNG / wall-clock nondeterminism in library code.

Reproducibility is part of the parity contract: the golden-parity and
sharded-equivalence tests require that the same seed grows the same
trees on 1 or N devices. Global-state RNG (``np.random.rand`` et al.,
stdlib ``random.*``) breaks that silently — draw order then depends on
import order and whatever else touched the global stream. Library code
must thread an explicitly seeded ``np.random.default_rng(seed)`` /
``RandomState(seed)`` (the repo convention) or a jax PRNG key.

Also flagged: seeding any RNG from the wall clock
(``default_rng(time.time())``), which launders nondeterminism through
an otherwise-seeded constructor.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleContext
from . import register

_SEEDED_CTORS = {"default_rng", "RandomState", "SeedSequence", "Generator",
                 "PCG64", "Philox"}
_STDLIB_RANDOM_FNS = {
    "random", "randint", "uniform", "choice", "choices", "shuffle",
    "sample", "randrange", "gauss", "normalvariate", "betavariate",
    "expovariate", "getrandbits", "seed"}
_CLOCK_CALLS = ("time.time", "time.time_ns", "datetime.datetime.now")


@register
class Nondeterminism:
    id = "JG005"
    name = "unseeded-nondeterminism"
    description = ("global np.random / stdlib random draws or wall-clock "
                   "seeding make runs irreproducible")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.call_target(node)
            if target is None:
                continue
            if target.startswith("numpy.random."):
                fn = target.split(".")[-1]
                if fn not in _SEEDED_CTORS:
                    out.append(ctx.finding(
                        self.id, node,
                        "`np.random.%s` draws from the process-global "
                        "RNG; use an explicitly seeded "
                        "`np.random.default_rng(seed)`" % fn))
                    continue
            elif target.startswith("random.") \
                    and target.split(".")[-1] in _STDLIB_RANDOM_FNS \
                    and ctx.aliases.get("random", "random") == "random":
                out.append(ctx.finding(
                    self.id, node,
                    "stdlib `%s` uses the global RNG; use a seeded "
                    "`np.random.default_rng`" % target))
                continue
            if target.split(".")[-1] in _SEEDED_CTORS or \
                    target == "numpy.random.seed":
                if any(isinstance(sub, ast.Call)
                       and ctx.call_target(sub) in _CLOCK_CALLS
                       for a in node.args for sub in ast.walk(a)):
                    out.append(ctx.finding(
                        self.id, node,
                        "RNG seeded from the wall clock is "
                        "nondeterministic; take the seed from config"))
        return out
