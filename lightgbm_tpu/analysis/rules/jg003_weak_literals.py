"""JG003 — dtype-defaulted literals: the f64-promotion (tie-flip) class.

The package runs under ``jax_enable_x64`` for reference-parity f64 host
math, which flips every *dtype-defaulted* construction to f64/i64. The
pinned persist-f32 vs v1-f64 tie-flip divergence
(tests/test_known_divergence.py) is exactly this class biting: a value
silently materialized at f64 joins f32 kernel math, the extra precision
shifts a noise-gain split's tie, and two otherwise-identical runs grow
different trees. Three statically checkable shapes:

* ``jnp.zeros(shape)`` / ``ones`` / ``full`` / ``empty`` / ``arange`` /
  ``eye`` with no dtype → f64/i64 arrays under x64 (``zeros_like``
  et al. inherit and are fine);
* ``jnp.asarray(0.5)`` / ``jnp.array([...])`` of bare literals with no
  dtype → f64 scalars/arrays (asarray of an existing typed array keeps
  its dtype and is fine);
* ``jnp.where(cond, 1.0, -1.0)`` with BOTH branches literal → a
  materialized default-float (f64) select; one literal branch keeps the
  other operand's dtype through weak typing and stays silent;
* plus, inside kernel-pattern functions only: bare float literals in
  arithmetic/comparisons (``hb * cf + 0.5``) — weak-typed today, but
  one non-weak operand away from promoting the whole expression, and
  cheap to make explicit with ``jnp.float32(...)``.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, ModuleContext
from . import register

_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange", "eye",
                 "linspace"}
_FROM_VALUE = {"asarray", "array"}
_JNP = "jax.numpy."
# calls whose direct literal args are dtype-explicit already
_CAST_CALLS = {"jax.numpy.float32", "jax.numpy.float64", "jax.numpy.int32",
               "numpy.float32", "numpy.float64", "numpy.int32"}


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value,
                                                     (int, float)):
        return not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                    (ast.USub, ast.UAdd)):
        return _is_literal(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return bool(node.elts) and all(_is_literal(e) for e in node.elts)
    if isinstance(node, ast.BinOp):   # 2.0 ** 30 style constant folds
        return _is_literal(node.left) and _is_literal(node.right)
    return False


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                    (ast.USub, ast.UAdd)):
        return _is_float_literal(node.operand)
    return False


@register
class WeakTypeLiterals:
    id = "JG003"
    name = "dtype-defaulted-literal"
    description = ("dtype-defaulted jnp construction or bare-literal "
                   "kernel arithmetic promotes to f64/i64 under x64 "
                   "(the persist-f32 tie-flip class)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                msg = self._check_call(ctx, node)
                if msg:
                    out.append(ctx.finding(self.id, node, msg))
            elif isinstance(node, (ast.BinOp, ast.Compare)) \
                    and ctx.in_kernel_scope(node):
                msg = self._check_kernel_arith(ctx, node)
                if msg:
                    out.append(ctx.finding(self.id, node, msg))
        return out

    # -- dtype-defaulted constructors ---------------------------------
    def _check_call(self, ctx: ModuleContext, node: ast.Call) -> str:
        target = ctx.call_target(node)
        if target is None or not target.startswith(_JNP):
            return ""
        fn = target[len(_JNP):]
        has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
        if fn in _CONSTRUCTORS:
            # positional dtype: zeros(shape, dt) / full(shape, v, dt);
            # arange's 2nd..4th positionals are bounds, never a dtype
            pos_dtype = (len(node.args) >= 2 and fn not in
                         ("full", "arange")) or \
                        (fn == "full" and len(node.args) >= 3)
            if not (has_dtype or pos_dtype):
                return ("`jnp.%s` without dtype defaults to f64/i64 "
                        "under x64; pass an explicit dtype" % fn)
        elif fn in _FROM_VALUE:
            pos_dtype = len(node.args) >= 2
            if not (has_dtype or pos_dtype) and node.args \
                    and _is_literal(node.args[0]):
                return ("`jnp.%s` of a bare literal defaults to f64/i64 "
                        "under x64; pass an explicit dtype" % fn)
        elif fn == "where" and len(node.args) == 3 \
                and _is_float_literal(node.args[1]) \
                and _is_float_literal(node.args[2]) \
                and not self._immediately_cast(ctx, node):
            return ("`jnp.where` with two literal branches materializes "
                    "a default-float (f64 under x64) array; cast to the "
                    "consumer's dtype or use `.astype`")
        return ""

    def _immediately_cast(self, ctx: ModuleContext, node: ast.Call) -> bool:
        """True when the call's result is directly `.astype(...)`-ed or
        wrapped in an explicit cast — the fix this rule asks for."""
        parent = ctx.parent.get(node)
        if isinstance(parent, ast.Attribute) and parent.attr == "astype":
            return True
        if isinstance(parent, ast.Call) \
                and ctx.call_target(parent) in _CAST_CALLS:
            return True
        return False

    # -- bare literals in kernel arithmetic ---------------------------
    def _literal_operand(self, ctx, node) -> Optional[ast.AST]:
        if isinstance(node, ast.BinOp):
            operands = [node.left, node.right]
        else:
            operands = [node.left] + list(node.comparators)
        lits = [op for op in operands if _is_float_literal(op)]
        if not lits or len(lits) == len(operands):
            return None            # pure-literal expressions are static
        return lits[0]

    def _check_kernel_arith(self, ctx: ModuleContext, node) -> str:
        # skip when the literal is already inside an explicit cast call
        parent = ctx.parent.get(node)
        if isinstance(parent, ast.Call) \
                and ctx.call_target(parent) in _CAST_CALLS:
            return ""
        lit = self._literal_operand(ctx, node)
        if lit is None:
            return ""
        return ("bare float literal in kernel arithmetic; wrap it as "
                "`jnp.float32(...)` so the expression cannot promote "
                "under x64")
