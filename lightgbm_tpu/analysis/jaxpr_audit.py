"""Jaxpr/HLO structural audits of the real TPU entry points.

Where the AST linter reasons about source text, this module traces the
actual hot-path programs with abstract inputs (``jax.make_jaxpr`` — no
device execution, runs fine on CPU) and asserts invariants on the IR:

* **persist-f32 kernels stay f32** — no ``convert_element_type`` to
  f64 anywhere in the jaxprs of ``hist_window`` (both variants),
  ``scan_pair``, ``scan_blocks``, the persist ``split_pass``, or the
  batched level-program kernels (``level_pass`` / ``level_seg_hist`` /
  the wide ``scan_pair`` batch the level split-find feeds). This
  is the machine-checked half of the tie-flip characterization
  (tests/test_known_divergence.py tracks the residual v1-vs-persist
  gap; this audit pins that the persist side cannot silently widen).
* **no host callbacks/transfers inside loop bodies** — the predict
  traversal's ``fori_loop``/``scan`` bodies (and the kernels') must be
  free of ``pure_callback``/``io_callback``/``debug_callback``/
  ``device_put``: one of those inside a loop serializes the pipeline
  per level instead of per batch.
* **donation is real** — the predict runtime's jit wrapper must record
  input-output aliasing in its lowered IR when donation is requested,
  and the persist split kernel must alias its payload in/out (the
  in-place partition the whole design assumes).
* **the serve ladder bound holds analytically** — every batch size in
  [1, max_batch] maps into at most ceil(log2(max/min)) + 1 buckets.

Each audit returns an :class:`AuditResult`; audits that need pallas
report ``skipped`` on builds without it instead of failing the gate.

The traversal layer lives in :mod:`dataflow` since PR 13: one shared
walk covers every sub-jaxpr carrier (``pjit``, ``scan``, ``while``,
``cond``, ``custom_jvp/vjp``, ``closed_call``) AND the consts closed
over inside them — the old per-check recursion missed an f64 constant
captured in a ``custom_jvp`` body because consts are not equation
outputs.  The f64-free walk, the host-prim-in-loop check, and the
aliasing checks are now small queries against that engine.  Setting
``LGBTPU_SEED_CUSTOM_JVP_F64=1`` arms the seeded regression fixture
(an f64 constant closed over inside a ``jax.custom_jvp`` body) as a
live audit, flipping the gate to exit 1 — the machine-checked proof
the detector detects.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import events as telemetry
from . import dataflow
from .dataflow import alias_sites, find_f64_consts, iter_eqns  # noqa: F401 — re-exported audit queries

C_AUDIT_FAIL = "analysis::audit_fail"

# re-exported for the transfer auditor and older callers
_HOST_PRIMS = dataflow.HOST_PRIMS

_F64 = np.dtype("float64")

SEED_CUSTOM_JVP_ENV = "LGBTPU_SEED_CUSTOM_JVP_F64"


@dataclass
class AuditResult:
    name: str
    ok: bool
    detail: str = ""
    skipped: bool = False

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "detail": self.detail,
                "skipped": self.skipped}


# ---------------------------------------------------------------------------
# jaxpr queries (all on the shared dataflow walk)
# ---------------------------------------------------------------------------

def find_f64_converts(jaxpr) -> List[str]:
    out = []
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name == "convert_element_type" \
                and np.dtype(eqn.params.get("new_dtype")) == _F64:
            out.append(str(eqn))
    return out


def find_f64_outputs(jaxpr) -> List[str]:
    """Ops *producing* f64 anywhere (stricter than converts: catches f64
    constants and dtype-defaulted iota/broadcast)."""
    out = []
    for eqn, _ in iter_eqns(jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None \
                    and getattr(aval, "dtype", None) == _F64:
                out.append("%s -> %s" % (eqn.primitive.name, aval))
    return out


def find_host_prims_in_loops(jaxpr) -> List[str]:
    out = []
    for eqn, depth in iter_eqns(jaxpr):
        if depth > 0 and eqn.primitive.name in _HOST_PRIMS:
            out.append(eqn.primitive.name)
    return out


def _audit_jaxpr(name: str, closed, forbid_f64: bool = True,
                 strict_f64: bool = False) -> AuditResult:
    jaxpr = closed.jaxpr
    problems: List[str] = []
    if forbid_f64:
        finder = find_f64_outputs if strict_f64 else find_f64_converts
        hits = finder(jaxpr)
        if strict_f64:
            # consts are not equation outputs: an f64 array closed over
            # (even one narrowed immediately inside a custom_jvp body)
            # only shows up on the const walk
            hits = find_f64_consts(closed) + hits
        if hits:
            problems.append("f64 values in a persist-f32 program: %s"
                            % "; ".join(hits[:3]))
    loops = find_host_prims_in_loops(jaxpr)
    if loops:
        problems.append("host/transfer primitives inside loop bodies: %s"
                        % ", ".join(sorted(set(loops))))
    return AuditResult(name=name, ok=not problems,
                       detail="; ".join(problems))


def _skip(name: str, why: str) -> AuditResult:
    return AuditResult(name=name, ok=True, detail=why, skipped=True)


# ---------------------------------------------------------------------------
# individual audits
# ---------------------------------------------------------------------------

def audit_hist_window() -> AuditResult:
    """Both histogram kernel variants (radix W=256, one-hot W<=64) trace
    f64-free with f32 gradients."""
    from ..ops.pallas_compat import HAS_PALLAS
    name = "hist_window_f32"
    if not HAS_PALLAS:
        return _skip(name, "pallas unavailable")
    from ..ops.pallas_histogram import hist_window
    problems = []
    for w, G, C in ((256, 3, 1024), (64, 5, 512)):
        bins = jax.ShapeDtypeStruct((G, C), jnp.int32)
        vec = jax.ShapeDtypeStruct((C,), jnp.float32)
        closed = jax.make_jaxpr(
            lambda b, g, h, _w=w: hist_window(b, g, h, w=_w))(
                bins, vec, vec)
        r = _audit_jaxpr(name, closed, strict_f64=True)
        if not r.ok:
            problems.append("w=%d: %s" % (w, r.detail))
    return AuditResult(name=name, ok=not problems,
                       detail="; ".join(problems))


def audit_scan_pair() -> AuditResult:
    from ..ops.pallas_compat import HAS_PALLAS
    name = "scan_pair_f32"
    if not HAS_PALLAS:
        return _skip(name, "pallas unavailable")
    from ..ops.pallas_scan import scan_pair
    Fp, Wp = 8, 128
    f32 = jnp.float32
    closed = jax.make_jaxpr(scan_pair)(
        jax.ShapeDtypeStruct((2, 8), f32),
        jax.ShapeDtypeStruct((2, Fp, Wp), f32),
        jax.ShapeDtypeStruct((2, Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((8, Fp), f32))
    return _audit_jaxpr(name, closed, strict_f64=True)


def audit_scan_blocks() -> AuditResult:
    from ..ops.pallas_compat import HAS_PALLAS
    name = "scan_blocks_f32"
    if not HAS_PALLAS:
        return _skip(name, "pallas unavailable")
    from ..ops.pallas_scan import BM_ROWS, scan_blocks
    Gp, Wp = 8, 128
    f32 = jnp.float32
    closed = jax.make_jaxpr(
        lambda s, g, h, m: scan_blocks(s, g, h, m, do_fix=True))(
            jax.ShapeDtypeStruct((2, 9), f32),
            jax.ShapeDtypeStruct((2, Gp, Wp), f32),
            jax.ShapeDtypeStruct((2, Gp, Wp), f32),
            jax.ShapeDtypeStruct((BM_ROWS, Gp, Wp), f32))
    return _audit_jaxpr(name, closed, strict_f64=True)


def audit_persist_split_pass() -> AuditResult:
    """The Mosaic split_pass on a toy payload geometry: f64-free, and
    the payload must be donated (input_output_aliases) — the in-place
    partition contract."""
    from ..ops.pallas_compat import HAS_PALLAS
    name = "persist_split_pass"
    if not HAS_PALLAS:
        return _skip(name, "pallas unavailable")
    from ..ops.pallas_grow import make_split_pass
    WPA, NP, G, nbw = 8, 1024, 2, 2
    plan = ((0, 0, 255), (1, 0, 255))
    sp = make_split_pass(WPA, NP, G, plan, nbw, C=256)
    closed = jax.make_jaxpr(sp)(
        jax.ShapeDtypeStruct((WPA, NP), jnp.uint32),
        jax.ShapeDtypeStruct((16,), jnp.int32))
    res = _audit_jaxpr(name, closed, strict_f64=True)
    if not res.ok:
        return res
    aliased = any(ioa for prim, ioa in alias_sites(closed.jaxpr)
                  if "pallas_call" in prim)
    if not aliased:
        return AuditResult(
            name=name, ok=False,
            detail="split_pass pallas_call lost its payload "
                   "input_output_aliases (in-place partition broken)")
    return res


def audit_persist_level_pass() -> AuditResult:
    """The batched LEVEL program kernels (PR 7) on a toy payload
    geometry: the multi-leaf ``level_pass`` must trace f64-free and keep
    the payload ``input_output_aliases`` (the in-place multi-leaf
    partition contract — one lost alias turns every level into a full
    payload copy); the batched ``level_seg_hist`` and a wider-than-pair
    ``scan_pair`` batch (the level split-find shape) must also stay
    f32. This is the level-program extension of
    :func:`audit_persist_split_pass` — the level path batches S leaves
    per launch, so a silent widening or alias loss costs S× more than
    on the per-split path."""
    from ..ops.pallas_compat import HAS_PALLAS
    name = "persist_level_pass"
    if not HAS_PALLAS:
        return _skip(name, "pallas unavailable")
    from ..ops.pallas_grow import make_level_pass, make_level_seg_hist
    from ..ops.pallas_scan import scan_pair
    WPA, NP, G, nbw = 8, 1024, 2, 2
    plan = ((0, 0, 255), (1, 0, 255))
    S_max, T_max = 4, 16
    i32 = jnp.int32
    lp = make_level_pass(WPA, NP, G, plan, nbw, S_max, T_max, C=256)
    closed = jax.make_jaxpr(lp)(
        jax.ShapeDtypeStruct((WPA, NP), jnp.uint32),
        jax.ShapeDtypeStruct((S_max, 16), i32),
        jax.ShapeDtypeStruct((T_max,), i32),
        jax.ShapeDtypeStruct((S_max,), i32),
        jax.ShapeDtypeStruct((), i32))
    res = _audit_jaxpr(name, closed, strict_f64=True)
    if not res.ok:
        return res
    aliased = any(ioa for prim, ioa in alias_sites(closed.jaxpr)
                  if "pallas_call" in prim)
    if not aliased:
        return AuditResult(
            name=name, ok=False,
            detail="level_pass pallas_call lost its payload "
                   "input_output_aliases (in-place multi-leaf "
                   "partition broken)")
    ls = make_level_seg_hist(WPA, NP, G, plan, nbw, S_max, T_max, C=256)
    closed_s = jax.make_jaxpr(ls)(
        jax.ShapeDtypeStruct((WPA, NP), jnp.uint32),
        jax.ShapeDtypeStruct((S_max, 4), i32),
        jax.ShapeDtypeStruct((T_max,), i32),
        jax.ShapeDtypeStruct((S_max,), i32),
        jax.ShapeDtypeStruct((), i32))
    res_s = _audit_jaxpr(name, closed_s, strict_f64=True)
    if not res_s.ok:
        return res_s
    B, Fp, Wp = 2 * S_max, 8, 128
    f32 = jnp.float32
    closed_b = jax.make_jaxpr(scan_pair)(
        jax.ShapeDtypeStruct((B, 8), f32),
        jax.ShapeDtypeStruct((B, Fp, Wp), f32),
        jax.ShapeDtypeStruct((B, Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((Fp, Wp), f32),
        jax.ShapeDtypeStruct((8, Fp), f32))
    return _audit_jaxpr(name, closed_b, strict_f64=True)


def _toy_ensemble(num_class: int = 1):
    """Hand-built 3-tree CompiledEnsemble (two depth buckets, one
    categorical bitset node) — no training required. With num_class=3
    the 3 trees become one iteration of 3 classes, which makes the raw
    output shape [rows, 3] match an X of 3 features — the geometry the
    donation audit needs for input-output aliasing to be legal."""
    from ..predict.compile import CompiledEnsemble, TreeBucket
    i32 = np.int32
    b1 = TreeBucket(
        depth=2,
        tree_pos=np.array([0, 2], i32),
        split_feature=np.array([[0, 1, 0], [1, 0, 2]], i32),
        threshold=np.array([[0.5, -1.0, 1.0], [0.0, 0.25, 0.5]]),
        decision_type=np.array([[2, 0, 0], [1, 0, 2]], i32),
        left=np.array([[1, -1, -3], [1, -1, -3]], i32),
        right=np.array([[2, -2, -4], [2, -2, -4]], i32),
        leaf_value=np.array([[0.1, -0.2, 0.3, -0.4],
                             [0.5, -0.6, 0.7, -0.8]]),
        cat_offset=np.array([[0, 0, 0], [0, 0, 0]], i32),
        cat_nwords=np.array([[0, 0, 0], [1, 0, 0]], i32),
        cat_words=np.array([0b1010], np.uint32))
    b2 = TreeBucket(
        depth=1,
        tree_pos=np.array([1], i32),
        split_feature=np.array([[2]], i32),
        threshold=np.array([[0.0]]),
        decision_type=np.array([[0]], i32),
        left=np.array([[-1]], i32),
        right=np.array([[-2]], i32),
        leaf_value=np.array([[0.05, -0.05]]),
        cat_offset=np.array([[0]], i32),
        cat_nwords=np.array([[0]], i32),
        cat_words=np.array([0], np.uint32))
    return CompiledEnsemble(buckets=(b1, b2), num_trees=3,
                            num_tree_per_iteration=num_class,
                            average_output=False, max_feature_idx=2)


def audit_predict_traversal() -> AuditResult:
    """The f32 predict runtime traces f64-free and keeps its
    fori_loop/scan bodies free of host callbacks/transfers."""
    from ..predict.runtime import TPUPredictor
    name = "predict_traversal_f32"
    pred = TPUPredictor(_toy_ensemble(), dtype="f32", donate=False)
    X = jax.ShapeDtypeStruct((64, 3), jnp.float32)
    closed = jax.make_jaxpr(
        lambda x: pred._forward_raw(x, False))(X)
    return _audit_jaxpr(name, closed, strict_f64=True)


def audit_predict_donation() -> AuditResult:
    """With donation requested, the lowered predict program must record
    input-output buffer aliasing (jax drops donation silently when the
    wrapper loses the donate_argnums — this pins it structurally). Uses
    the 3-class toy so the [rows, K] output is alias-compatible with the
    [rows, F] input; an alias-incompatible program cannot witness
    donation at all."""
    import warnings

    from ..predict.runtime import TPUPredictor
    name = "predict_donation"
    pred = TPUPredictor(_toy_ensemble(num_class=3), dtype="f32",
                        donate=True)
    X = jax.ShapeDtypeStruct((64, 3), jnp.float32)
    with warnings.catch_warnings():
        # CPU emits "donated buffers were not usable" for the aliases it
        # cannot honor; the audit reads the IR, not the backend support
        warnings.simplefilter("ignore")
        txt = pred._raw_fn.lower(X, False).as_text()
    ok = ("tf.aliasing_output" in txt) or ("jax.buffer_donor" in txt)
    return AuditResult(
        name=name, ok=ok,
        detail="" if ok else "donate=True produced no input-output "
                             "aliasing in the lowered IR")


def audit_serve_ladder() -> AuditResult:
    """Every batch size in [1, max_batch] lands in at most
    ceil(log2(max/min)) + 1 buckets — the compile bound BatchServer
    guarantees and predict::serve_compile pins at runtime."""
    from ..predict.serve import BatchServer
    name = "serve_ladder_bound"

    class _Stub:
        _dtype = jnp.float32
    problems = []
    for mn, mx in ((256, 1 << 16), (64, 1024), (128, 128)):
        srv = BatchServer.__new__(BatchServer)
        srv.min_batch = mn
        srv.max_batch = mx
        buckets = {srv.bucket_rows(n) for n in range(1, mx + 1)}
        bound = int(np.log2(mx // mn)) + 1
        if len(buckets) > bound:
            problems.append("ladder [%d, %d]: %d buckets > bound %d"
                            % (mn, mx, len(buckets), bound))
    return AuditResult(name=name, ok=not problems,
                       detail="; ".join(problems))


def build_fused_iteration_programs():
    """Trace the fused boosting-iteration drivers (PR 17) on a toy
    binary dataset: the gbdt k-batch scan and the RF variant, both as
    unjitted bodies (``wrap_jit=False`` — the jaxpr walk needs the
    scan structure, not the launch wrapper), plus the lowered-IR
    donation witness for the jitted gbdt driver (the payload carry
    must alias input to output or every batch pays a full payload
    copy). Built once per process through ``precision_audit._memo``
    so transfer_audit walks the SAME traces. Returns
    ``{"programs": [(name, ClosedJaxpr), ...], "donated": bool}``."""
    import warnings

    from ..config import Config
    from ..data.dataset import BinnedDataset
    from ..objectives.base import create_objective
    from ..ops.grow_persist import (build_assets, make_persist_grower,
                                    make_scan_driver)
    from ..treelearner.serial import SerialTreeLearner

    rng = np.random.RandomState(7)
    n, F, k = 256, 6, 2
    X = rng.rand(n, F)
    y = (rng.rand(n) > 0.5).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 7,
                  "max_bin": 63, "verbosity": -1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    learner = SerialTreeLearner(cfg, ds)
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    # score64: the off-TPU trace carries the v1-parity f64 score
    # emulation — the mode DART/RF bit-exactness rides on
    assets = build_assets(ds, ds.metadata.label, score64=True)
    gr = make_persist_grower(assets, learner.meta, learner.grow_config,
                             kernel_impl="xla")
    gmode, gfn = obj.device_gradients()
    gc = learner.grow_config
    pay = gr.init_carry(jnp.asarray(assets.pay0),
                        jnp.zeros((n,), jnp.float64))
    fmasks = jnp.ones((k, gc.num_features), bool)
    iters = jnp.arange(k, dtype=jnp.int32)
    run = make_scan_driver(gr, gc, k, gfn, grad_mode=gmode,
                           wrap_jit=False)
    gbdt_args = (pay, fmasks, jnp.zeros((k, 2), jnp.uint32), iters,
                 learner.params, jnp.asarray(0.1, jnp.float64), ())
    run_rf = make_scan_driver(gr, gc, k, gfn, mode="rf",
                              wrap_jit=False)
    t = jnp.arange(k, dtype=jnp.float64)
    closed_r = jax.make_jaxpr(run_rf)(
        pay, fmasks, jnp.ones((k, n), jnp.float32),
        jnp.stack([t, 1.0 / (t + 1.0)], axis=1), iters,
        learner.params, jnp.asarray(0.25, jnp.float64))
    with warnings.catch_warnings():
        # CPU warns about donated buffers it cannot honor; the audit
        # reads the IR, not the backend support
        warnings.simplefilter("ignore")
        # one trace serves both the jaxpr walk and the donation
        # witness in the lowered IR
        traced = jax.jit(run, donate_argnums=(0,)).trace(*gbdt_args)
        closed_g = traced.jaxpr
        txt = traced.lower().as_text()
    donated = ("tf.aliasing_output" in txt) or ("jax.buffer_donor" in txt)
    # the fixture only traces the drivers — no stats ever accumulate —
    # but the flush discipline the health audit pins still applies to
    # the owner of any driver site, and on an untrained learner this is
    # an immediate no-op
    learner.flush_level_stats()
    return {"programs": [("fused_iter_gbdt", closed_g),
                         ("fused_iter_rf", closed_r)],
            "donated": donated}


def audit_fused_iteration() -> AuditResult:
    """The whole-iteration fused programs (PR 17): the objectives'
    device gradient kernels must trace strictly f64-free in the
    persist-f32 contract; the gbdt and RF k-iteration drivers must
    keep their scan bodies free of host callbacks/transfers (tree
    boundaries never leave the device); and the jitted gbdt driver
    must witness payload donation in the lowered IR (the carry
    aliasing the whole fast path leans on). The driver traces run the
    score64 emulation, so the f64 ban applies to the standalone
    gradient kernels — the only new math the fusion moved on-device —
    not the (deliberately) widened score rows."""
    from . import precision_audit as pa
    name = "fused_iteration"
    problems: List[str] = []
    for gname, closed, _rng, _bless in pa._memo(
            "fused_grads", pa._fused_grad_programs):
        r = _audit_jaxpr(gname, closed, strict_f64=True)
        if not r.ok:
            problems.append("%s: %s" % (gname, r.detail))
    art = pa._memo("fused_drivers", build_fused_iteration_programs)
    for dname, closed in art["programs"]:
        loops = find_host_prims_in_loops(closed.jaxpr)
        if loops:
            problems.append(
                "%s: host/transfer primitives inside the iteration "
                "scan: %s" % (dname, ", ".join(sorted(set(loops)))))
    if not art["donated"]:
        problems.append("fused_iter_gbdt: donation produced no payload "
                        "input-output aliasing in the lowered IR "
                        "(every batch would copy the payload)")
    return AuditResult(name=name, ok=not problems,
                       detail="; ".join(problems[:3]))


def build_custom_jvp_f64_fixture():
    """The satellite regression fixture: an f64 constant closed over
    inside a ``jax.custom_jvp`` body, narrowed to f32 before use — no
    equation ever OUTPUTS f64 outside a benign staging ``device_put``,
    so the old recursive walk passed it while the f64 data silently
    participated.  Returns the traced ClosedJaxpr."""
    const64 = np.arange(4, dtype=np.float64) * 1.5

    @jax.custom_jvp
    def leaky(x):
        return x * jnp.asarray(const64).astype(jnp.float32)

    @leaky.defjvp
    def leaky_jvp(primals, tangents):
        return leaky(primals[0]), tangents[0]

    return jax.make_jaxpr(lambda x: leaky(x) + jnp.float32(1))(
        jax.ShapeDtypeStruct((4,), jnp.float32))


def audit_seeded_custom_jvp_f64() -> AuditResult:
    """Armed by ``LGBTPU_SEED_CUSTOM_JVP_F64=1``: runs the strict f64
    audit against the seeded fixture, which MUST fail — proving the
    const-aware walk sees through custom_jvp call primitives."""
    res = _audit_jaxpr("seeded_custom_jvp_f64",
                       build_custom_jvp_f64_fixture(), strict_f64=True)
    if res.ok:
        return AuditResult(
            name="seeded_custom_jvp_f64", ok=False,
            detail="the seeded f64-const-in-custom_jvp fixture passed "
                   "the strict f64 audit — the const walk regressed")
    return res


AUDITS: Tuple[Callable[[], AuditResult], ...] = (
    audit_hist_window,
    audit_scan_pair,
    audit_scan_blocks,
    audit_persist_split_pass,
    audit_persist_level_pass,
    audit_predict_traversal,
    audit_predict_donation,
    audit_serve_ladder,
    audit_fused_iteration,
)


def run_audits(names: Optional[List[str]] = None) -> List[AuditResult]:
    """Run all (or the named) audits; an audit that raises reports as a
    failed result rather than killing the gate."""
    audits = AUDITS
    if os.environ.get(SEED_CUSTOM_JVP_ENV, "") not in ("", "0"):
        # the seeded true-positive: flips the gate to exit 1 on demand
        audits = audits + (audit_seeded_custom_jvp_f64,)
    out: List[AuditResult] = []
    for fn in audits:
        nm = fn.__name__.replace("audit_", "")
        if names and nm not in names and fn.__name__ not in names:
            continue
        try:
            out.append(fn())
        except Exception as e:  # pragma: no cover - defensive
            out.append(AuditResult(name=nm, ok=False,
                                   detail="audit raised: %r" % e))
    failed = sum(1 for r in out if not r.ok)
    if failed:
        telemetry.count(C_AUDIT_FAIL, failed, category="analysis")
    return out
