"""health_covered auditor: every persist/level driver consumer must flush
the device-side numerics health stats.

The runtime numerics sentinel only works if the device-accumulated
health vector actually reaches the telemetry registry: a program built
on ``make_scan_driver`` whose stats output is dropped (or whose owner
never calls the canonical flush) trains blind — NaN storms and margin
collapses happen on the chip and nobody ever sees them. Same shape as
the ``collective_observed`` audit: enumerate the driver-construction
sites statically, fail on any site with no flush path
(:func:`telemetry.health.flush_device_stats` directly, or
``flush_level_stats`` — the learner wrapper around it).

Coverage is inheritance-aware: the sharded learner builds its driver in
``parallel/learners.py`` but rides the serial learner's
``train_arrays_scan_persist``/``flush_level_stats`` loop — a driver
site inside a class is covered when the class OR any base in the
audited file set flushes. Scope: the graftlint include paths (the
package itself); drivers built in tests/fixtures are out of scope.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from ..telemetry import events as telemetry
from .config import GraftlintConfig, load_config
from .jaxpr_audit import AuditResult

C_UNOBSERVED = "analysis::health_unobserved"

# building one of these yields a program whose stats output carries the
# numerics health vector (ops/grow_persist STATS_LEN layout)
DRIVER_BUILDERS = ("make_scan_driver",)
# flush_level_stats is the learner-side wrapper around the canonical
# telemetry.health.flush_device_stats
FLUSH_CALLS = ("flush_device_stats", "flush_level_stats")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _base_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


class _ModuleScan:
    """One module's driver sites, flush calls, and class graph."""

    def __init__(self, source: str, relpath: str):
        self.relpath = relpath
        self.sites: List[tuple] = []      # (lineno, builder, class|None)
        self.module_flushes = False
        self.classes: Dict[str, dict] = {}  # name -> {bases, flushes}
        self.error: Optional[str] = None
        try:
            tree = ast.parse(source)
        except SyntaxError as exc:
            self.error = "%s: unparsable (%s)" % (relpath, exc)
            return
        self._walk(tree, None)

    def _walk(self, node, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self.classes.setdefault(
                    child.name,
                    {"bases": [b for b in map(_base_name, child.bases)
                               if b], "flushes": False})
                self._walk(child, child.name)
                continue
            if isinstance(child, ast.Call):
                name = _call_name(child)
                if name in DRIVER_BUILDERS:
                    self.sites.append((child.lineno, name, cls))
                elif name in FLUSH_CALLS:
                    if cls is None:
                        self.module_flushes = True
                    else:
                        self.classes[cls]["flushes"] = True
            self._walk(child, cls)


def _evaluate(scans: List[_ModuleScan]) -> dict:
    """Resolve flush coverage over the combined class graph (a class
    flushes if it or any transitive base — matched BY NAME across the
    audited set — contains a flush call)."""
    classes: Dict[str, dict] = {}
    for sc in scans:
        classes.update(sc.classes)

    def class_flushes(name: str, seen=None) -> bool:
        seen = seen or set()
        if name in seen or name not in classes:
            return False
        seen.add(name)
        info = classes[name]
        return info["flushes"] or any(class_flushes(b, seen)
                                      for b in info["bases"])

    findings: List[str] = []
    sites = 0
    for sc in scans:
        if sc.error:
            findings.append(sc.error)
            continue
        for line, builder, cls in sc.sites:
            sites += 1
            covered = sc.module_flushes or (cls is not None
                                            and class_flushes(cls))
            if not covered:
                findings.append(
                    "%s:%d: %s(...) builds a persist/level driver but "
                    "nothing on its path flushes the numerics::* health "
                    "stats (call telemetry.health.flush_device_stats — "
                    "or the learner's flush_level_stats — on the stats "
                    "vector)" % (sc.relpath, line, builder))
    return {"driver_sites": sites, "findings": findings}


def _audited_files(config: GraftlintConfig) -> List[str]:
    out: List[str] = []
    for frag in config.include:
        ap = os.path.join(config.root, frag)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.append(frag)
            continue
        if not os.path.isdir(ap):
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      config.root).replace(os.sep, "/")
                if any(ex in rel for ex in config.exclude):
                    continue
                out.append(rel)
    return out


def compute_artifact(config: Optional[GraftlintConfig] = None) -> dict:
    config = config or load_config()
    scans: List[_ModuleScan] = []
    for rel in _audited_files(config):
        try:
            with open(os.path.join(config.root, rel), "r",
                      encoding="utf-8") as f:
                src = f.read()
        except OSError:    # pragma: no cover - racing file removal
            continue
        # cheap text pre-filter: the class graph only matters for files
        # that build drivers, flush, or define learner classes
        if not any(tok in src for tok in
                   DRIVER_BUILDERS + FLUSH_CALLS + ("TreeLearner",)):
            continue
        scans.append(_ModuleScan(src, rel))
    return _evaluate(scans)


def run(config: Optional[GraftlintConfig] = None,
        artifact=None) -> List[AuditResult]:
    name = "health_covered"
    try:
        art = artifact if isinstance(artifact, dict) \
            else compute_artifact(config)
    except Exception as e:      # pragma: no cover - defensive
        return [AuditResult(name=name, ok=False,
                            detail="auditor raised: %r" % e)]
    if art["findings"]:
        telemetry.count(C_UNOBSERVED, len(art["findings"]),
                        category="analysis")
    return [AuditResult(
        name=name, ok=not art["findings"],
        detail="; ".join(art["findings"][:3]) if art["findings"]
        else "%d persist-driver site(s) flush numerics::* health stats"
             % art["driver_sites"])]


def check_fixture(payload: str) -> List[str]:
    """Uniform fixture hook: findings for a source snippet (a module
    that builds a scan driver with/without a health-flush path)."""
    return _evaluate([_ModuleScan(
        payload, "lightgbm_tpu/treelearner/fixture.py")])["findings"]
