"""Plotting utilities.

TPU-native rebuild of python-package/lightgbm/plotting.py:
plot_importance (:29), plot_split_value_histogram (:145), plot_metric
(:251), plot_tree / create_tree_digraph (:365-650). matplotlib/graphviz are
imported lazily and gated like the reference compat layer.
"""
from __future__ import annotations

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError("%s must be a list/tuple of 2 elements" % obj_name)


def _to_booster(booster):
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel")


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    dpi=None, grid=True, precision=3, **kwargs):
    """Plot model feature importances (reference plotting.py:29-142)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance")
    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type=importance_type)
    feature_name = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty")
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                ("%." + str(precision) + "f") % x if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef=0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    """Histogram of split thresholds of one feature (plotting.py:145-248)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot")
    booster = _to_booster(booster)
    gbdt = booster._booster
    if isinstance(feature, str):
        feature = booster.feature_name().index(feature)
    values = []
    for tree in gbdt._used_models():
        ni = tree.num_leaves - 1
        for k in range(ni):
            if tree.split_feature[k] == feature and \
                    not (tree.decision_type[k] & 1):
                values.append(tree.threshold[k])
    if not values:
        raise ValueError("Cannot plot split value histogram, "
                         "as feature %d was not used in splitting" % feature)
    hist, bin_edges = np.histogram(values, bins=bins or min(len(values), 20))
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    width = width_coef * (bin_edges[1] - bin_edges[0])
    centred = (bin_edges[:-1] + bin_edges[1:]) / 2
    ax.bar(centred, hist, width=width, align="center", **kwargs)
    if title is not None:
        title = title.replace("@feature@", str(feature)) \
                     .replace("@index/name@",
                              "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, dpi=None,
                grid=True):
    """Plot metric curves from evals_result (plotting.py:251-362)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric")
    if isinstance(booster, dict):
        eval_results = booster
    else:
        from .sklearn import LGBMModel
        if isinstance(booster, LGBMModel):
            eval_results = booster.evals_result_
        else:
            raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    if dataset_names is None:
        dataset_names = iter(eval_results.keys())
    name = None
    for name_ in dataset_names:
        metrics = eval_results[name_]
        if metric is None:
            metric = next(iter(metrics.keys()))
        results = metrics[metric]
        ax.plot(range(len(results)), results, label=name_)
        name = name_
    ax.legend(loc="best")
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _tree_to_digraph(tree, feature_names, precision=3, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree")
    graph = Digraph(**kwargs)

    def fmt(x):
        return ("%." + str(precision) + "g") % x

    def add(node_idx):
        if node_idx >= 0:
            f = tree.split_feature[node_idx]
            fname = (feature_names[f] if feature_names is not None
                     else "Column_%d" % f)
            is_cat = bool(tree.decision_type[node_idx] & 1)
            op = "==" if is_cat else "<="
            name = "split%d" % node_idx
            graph.node(name, "%s %s %s\ngain: %s" % (
                fname, op, fmt(tree.threshold[node_idx]),
                fmt(tree.split_gain[node_idx])))
            for child, tag in ((tree.left_child[node_idx], "yes"),
                               (tree.right_child[node_idx], "no")):
                cname = add(int(child))
                graph.edge(name, cname, label=tag)
            return name
        leaf = ~node_idx
        name = "leaf%d" % leaf
        graph.node(name, "leaf %d: %s" % (leaf, fmt(tree.leaf_value[leaf])))
        return name

    if tree.num_leaves <= 1:
        graph.node("leaf0", "leaf 0: %g" % tree.leaf_value[0])
    else:
        add(0)
    return graph


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        **kwargs):
    """Digraph of one tree (plotting.py:365-460)."""
    booster = _to_booster(booster)
    gbdt = booster._booster
    models = gbdt._used_models()
    if tree_index >= len(models):
        raise IndexError("tree_index is out of range.")
    return _tree_to_digraph(models[tree_index], gbdt.feature_names,
                            precision, **kwargs)


def plot_tree(booster, ax=None, tree_index=0, figsize=None, dpi=None,
              show_info=None, precision=3, **kwargs):
    """Render one tree with matplotlib (plotting.py:555-650)."""
    try:
        import matplotlib.image as mpimg
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree")
    import io
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                precision=precision, **kwargs)
    s = io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
