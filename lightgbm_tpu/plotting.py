"""Plotting utilities.

Same public surface as the reference python package's plotting module
(plot_importance / plot_split_value_histogram / plot_metric / plot_tree /
create_tree_digraph) so downstream code ports unchanged; implemented here
on top of this package's TreeArrays-backed model objects, with a shared
axis-decoration helper instead of per-function boilerplate. matplotlib and
graphviz are imported lazily.
"""
from __future__ import annotations

import numpy as np

from .basic import Booster


def _require_mpl(what="plot"):
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("matplotlib is required to %s" % what) from e
    return plt


def _pair(v, name):
    if not isinstance(v, (list, tuple)) or len(v) != 2:
        raise TypeError("%s must be a list/tuple of 2 elements" % name)
    return v


def _to_booster(booster):
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel")


def _decorate(ax, *, title, xlabel, ylabel, xlim=None, ylim=None, grid=True):
    """Apply the common title/label/limit/grid block to an axis."""
    if xlim is not None:
        ax.set_xlim(_pair(xlim, "xlim"))
    if ylim is not None:
        ax.set_ylim(_pair(ylim, "ylim"))
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _new_axis(plt, figsize, dpi):
    if figsize is not None:
        _pair(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    dpi=None, grid=True, precision=3, **kwargs):
    """Horizontal bar chart of feature importances."""
    plt = _require_mpl("plot importance")
    booster = _to_booster(booster)
    values = np.asarray(
        booster.feature_importance(importance_type=importance_type))
    if values.size == 0:
        raise ValueError("Booster's feature_importance is empty")
    names = np.asarray(booster.feature_name(), dtype=object)

    # ascending by importance so the largest bar lands on top of the chart
    order = np.argsort(values, kind="stable")
    if ignore_zero:
        order = order[values[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[max(0, len(order) - max_num_features):]
    if order.size == 0:
        raise ValueError("Booster's feature_importance is empty")
    values = values[order]
    names = names[order]

    if ax is None:
        ax = _new_axis(plt, figsize, dpi)
    ypos = np.arange(values.size)
    ax.barh(ypos, values, align="center", height=height, **kwargs)
    annotate = (lambda v: "%.*f" % (precision, v)) \
        if importance_type == "gain" else (lambda v: str(int(v)))
    for y, v in enumerate(values):
        ax.text(v + 1, y, annotate(v), va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels(names)
    return _decorate(ax, title=title, xlabel=xlabel, ylabel=ylabel,
                     xlim=xlim, ylim=ylim, grid=grid)


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef=0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    """Histogram of the split thresholds used for one feature."""
    plt = _require_mpl("plot split value histogram")
    booster = _to_booster(booster)
    gbdt = booster._booster
    by_name = isinstance(feature, str)
    if by_name:
        feature = booster.feature_name().index(feature)

    thresholds = [
        tree.threshold[k]
        for tree in gbdt._used_models()
        for k in range(tree.num_leaves - 1)
        if tree.split_feature[k] == feature
        and not (tree.decision_type[k] & 1)   # numerical splits only
    ]
    if not thresholds:
        raise ValueError("Cannot plot split value histogram, as feature %d "
                         "was not used in splitting" % feature)
    counts, edges = np.histogram(thresholds,
                                 bins=bins or min(len(thresholds), 20))
    if ax is None:
        ax = _new_axis(plt, figsize, dpi)
    ax.bar((edges[:-1] + edges[1:]) / 2, counts,
           width=width_coef * (edges[1] - edges[0]), align="center", **kwargs)
    if title is not None:
        title = title.replace("@feature@", str(feature)) \
                     .replace("@index/name@", "name" if by_name else "index")
    return _decorate(ax, title=title, xlabel=xlabel, ylabel=ylabel,
                     xlim=xlim, ylim=ylim, grid=grid)


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, dpi=None,
                grid=True):
    """Plot one metric's training curve(s) from recorded eval results."""
    plt = _require_mpl("plot metric")
    if isinstance(booster, dict):
        eval_results = booster
    else:
        from .sklearn import LGBMModel
        if not isinstance(booster, LGBMModel):
            raise TypeError("booster must be dict or LGBMModel")
        eval_results = booster.evals_result_
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if ax is None:
        ax = _new_axis(plt, figsize, dpi)
    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    for name in dataset_names:
        curves = eval_results[name]
        if metric is None:
            metric = next(iter(curves))
        series = curves[metric]
        ax.plot(np.arange(len(series)), series, label=name)
    ax.legend(loc="best")
    return _decorate(ax, title=title, xlabel=xlabel,
                     ylabel=metric if ylabel == "auto" else ylabel,
                     xlim=xlim, ylim=ylim, grid=grid)


def _tree_to_digraph(tree, feature_names, precision=3, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("graphviz is required to plot trees") from e
    graph = Digraph(**kwargs)

    def fmt(x):
        return ("%." + str(precision) + "g") % x

    def add(node_idx):
        if node_idx >= 0:
            f = tree.split_feature[node_idx]
            fname = (feature_names[f] if feature_names is not None
                     else "Column_%d" % f)
            is_cat = bool(tree.decision_type[node_idx] & 1)
            op = "==" if is_cat else "<="
            name = "split%d" % node_idx
            graph.node(name, "%s %s %s\ngain: %s" % (
                fname, op, fmt(tree.threshold[node_idx]),
                fmt(tree.split_gain[node_idx])))
            for child, tag in ((tree.left_child[node_idx], "yes"),
                               (tree.right_child[node_idx], "no")):
                cname = add(int(child))
                graph.edge(name, cname, label=tag)
            return name
        leaf = ~node_idx
        name = "leaf%d" % leaf
        graph.node(name, "leaf %d: %s" % (leaf, fmt(tree.leaf_value[leaf])))
        return name

    if tree.num_leaves <= 1:
        graph.node("leaf0", "leaf 0: %g" % tree.leaf_value[0])
    else:
        add(0)
    return graph


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        **kwargs):
    """Build a graphviz Digraph of one tree."""
    booster = _to_booster(booster)
    gbdt = booster._booster
    models = gbdt._used_models()
    if tree_index >= len(models):
        raise IndexError("tree_index is out of range.")
    return _tree_to_digraph(models[tree_index], gbdt.feature_names,
                            precision, **kwargs)


def plot_tree(booster, ax=None, tree_index=0, figsize=None, dpi=None,
              show_info=None, precision=3, **kwargs):
    """Render one tree to a matplotlib axis via graphviz."""
    plt = _require_mpl("plot tree")
    import io

    import matplotlib.image as mpimg
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                precision=precision, **kwargs)
    img = mpimg.imread(io.BytesIO(graph.pipe(format="png")))
    if ax is None:
        ax = _new_axis(plt, figsize, dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
