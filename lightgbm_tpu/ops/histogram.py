"""Gradient/hessian histogram construction on device.

TPU-native equivalent of the reference histogram kernels (dense col-wise
ConstructHistogram src/io/dense_bin.hpp:72-110, row-wise
Dataset::ConstructHistogramsMultiVal src/io/dataset.cpp:1198, and the OpenCL
kernels src/treelearner/ocl/histogram256.cl). Instead of per-thread/private
sub-histograms + atomics, the whole binned matrix lives in HBM as one
[num_data, num_groups] integer array whose entries are *global* bin ids
(group offset + in-group bin), and the histogram is a single scatter-add
(segment-sum) producing [total_bins] grad/hess sums. Single-feature groups
store every bin densely, so their histograms are complete by construction;
EFB-bundled sub-features still omit their most_freq bin (the group sentinel
takes those rows) and are repaired afterwards by ops.split.fix_histogram —
the analog of the reference's FixHistogram (src/io/dataset.cpp:1410).

The XLA path chunks rows through `lax.fori_loop` to bound the materialized
update tensor. On accelerators the growers use the one-hot MXU contraction
in ops/grow.py (_hist_chunk_contract) instead of this scatter-add.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit,
                   static_argnames=("total_bins", "rows_per_chunk", "dtype"))
def build_histogram(bins_global: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                    total_bins: int, rows_per_chunk: int = 0,
                    dtype=jnp.float32) -> jnp.ndarray:
    """Histogram over all features at once.

    Args:
      bins_global: [N, G] int32 global bin ids (row-major, group-bundled).
      grad, hess: [N] float32 per-row gradient/hessian (0 for masked-out rows).
      total_bins: static total number of global bins.
      rows_per_chunk: rows per scatter chunk; 0 = single shot.
      dtype: accumulator dtype. f64 sums of f32 per-row values are EXACT
        (each partial sum fits 53 mantissa bits at histogram scales), so
        f64 bins are order-independent — the reference CPU learner's
        double histograms (hist_t, src/treelearner/feature_histogram.hpp).

    Returns:
      [total_bins, 2] `dtype`: sum_grad, sum_hess per global bin.
    """
    n, g = bins_global.shape
    vals = jnp.stack([grad, hess], axis=-1).astype(dtype)  # [N, 2]

    if rows_per_chunk <= 0 or rows_per_chunk >= n:
        return _hist_one_shot(bins_global, vals, total_bins, dtype)

    num_chunks = (n + rows_per_chunk - 1) // rows_per_chunk
    pad = num_chunks * rows_per_chunk - n
    if pad:
        bins_global = jnp.pad(bins_global, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    bins_c = bins_global.reshape(num_chunks, rows_per_chunk, g)
    vals_c = vals.reshape(num_chunks, rows_per_chunk, 2)

    def body(i, acc):
        return acc + _hist_one_shot(bins_c[i], vals_c[i], total_bins, dtype)

    init = jnp.zeros((total_bins, 2), dtype=dtype)
    return jax.lax.fori_loop(0, num_chunks, body, init)


def _hist_one_shot(bins_global: jnp.ndarray, vals: jnp.ndarray,
                   total_bins: int, dtype=jnp.float32) -> jnp.ndarray:
    """One scatter-add over [N, G] -> [total_bins, 2]."""
    n, g = bins_global.shape
    flat_idx = bins_global.reshape(-1)                       # [N*G]
    # each row's (grad, hess) contributes to one bin per group
    flat_vals = jnp.broadcast_to(vals[:, None, :], (n, g, 2)).reshape(-1, 2)
    hist = jnp.zeros((total_bins, 2), dtype=dtype)
    return hist.at[flat_idx].add(flat_vals)


def masked_histogram(bins_global: jnp.ndarray, grad: jnp.ndarray,
                     hess: jnp.ndarray, mask: jnp.ndarray,
                     total_bins: int, rows_per_chunk: int = 0) -> jnp.ndarray:
    """Histogram restricted to rows where mask is True (a leaf's rows)."""
    m = mask.astype(grad.dtype)
    return build_histogram(bins_global, grad * m, hess * m,
                           total_bins=total_bins, rows_per_chunk=rows_per_chunk)
